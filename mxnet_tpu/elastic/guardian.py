"""Guardian plane: hang watchdog + preemption-safe drain.

Every recovery path PRs 7/8/11 built waits for the runtime to RAISE.
Production TPU jobs also die silently: a dispatch that hangs instead
of failing (a wedged PJRT tunnel, a deadlocked collective), and a
scheduler that SIGTERMs the process mid-step.  This module watches the
runtime instead of waiting for it:

* :class:`Guardian` — a daemon watchdog fed by HEARTBEATS from the
  existing telemetry step-owner seam (``telemetry.step_owner(owner,
  what)`` — ``CompiledStep``/``DataParallelTrainer`` steps and the
  serving ``Server``'s dispatch bracket all open one): a step/dispatch
  in flight longer than ``MXTPU_WATCHDOG_TIMEOUT`` emits a retained
  ``hang_suspected`` event carrying a per-thread stack dump, then
  escalates per ``MXTPU_WATCHDOG_ACTION``:

  - ``warn``    — the event + ``mxtpu_hangs_total`` only;
  - ``dump``    — additionally writes a flight-recorder artifact
    (the dump carries the stacks via the event it retains);
  - ``recover`` — additionally, when the hung dispatch finally
    resolves with the owner POISONED (the ``dispatch_hang`` drill —
    and a real TPU hang resolved by a device reset — consume the
    donated buffers), runs the owner's ``recover()`` through the same
    poison→``timed_recover`` protocol PR 7 built, ON the owning
    thread at the heartbeat's exit: a hung dispatch becomes a
    recovered step, not a dead job.  The step call that hung still
    raises (its buffers are gone), but the NEXT step trains on.

* :class:`PreemptionGuard` — SIGTERM/SIGINT handlers that reuse the
  drain leg of the live-resize protocol: finish the in-flight step
  (the handler runs on the main thread, so the current dispatch
  completes first), commit a checkpoint boundary
  (``manager.save(block=True, force=True)``), drain the serving
  scheduler (residents requeue-with-state and their replay manifest
  lands next to the checkpoint — :func:`drain_server`), emit a
  retained ``preempted`` event, and exit 0 — all inside
  ``MXTPU_DRAIN_DEADLINE_S``.  A SECOND signal force-exits (code 1)
  after dumping forensics.  ``exit_process=False`` makes the whole
  protocol in-process-testable (the tier-1 suite kills itself with
  ``os.kill`` and inspects the drain).

The ``preempt_signal`` fault point (``MXTPU_FAULT_INJECT``) is
consulted at the heartbeat's entry while this plane is installed: when
due, a REAL ``SIGTERM`` is delivered to the process so drills exercise
the actual signal path.

See docs/elasticity.md ("Guardian & chaos soak") for the escalation
ladder and the drain state machine.
"""
from __future__ import annotations

import itertools
import json
import os
import signal as _signal
import sys
import threading
import time
import traceback
import weakref
from typing import Dict, List, Optional

from ..base import MXNetError
from . import faults

__all__ = ["Guardian", "PreemptionGuard", "drain_server",
           "restore_drained_requests", "inflight", "thread_stacks"]

_lock = threading.Lock()
_tokens = itertools.count(1)
#: token -> in-flight heartbeat record (owner weakref, what, t0, the
#: Guardian that flagged it hung — None while healthy)
_inflight: Dict[int, dict] = {}
#: live Guardians/PreemptionGuards: the telemetry heartbeat hook is
#: installed iff this is nonzero (pay-for-what-you-watch)
_installed: List[object] = []


def _sync_hook():
    from .. import telemetry
    telemetry._hb_hook = (_hb_begin, _hb_end) if _installed else None


def _register(plane):
    with _lock:
        if plane not in _installed:
            _installed.append(plane)
        _sync_hook()


def _unregister(plane):
    with _lock:
        if plane in _installed:
            _installed.remove(plane)
        _sync_hook()


def inflight() -> List[dict]:
    """Snapshot of the currently-open heartbeats (watchdog input)."""
    now = time.monotonic()
    with _lock:
        return [{"what": r["what"], "seconds": now - r["t0"],
                 "hung": r["hung"] is not None}
                for r in _inflight.values()]


def _hb_begin(owner, what):
    # the preempt_signal drill rides the heartbeat: a due spec delivers
    # a REAL SIGTERM so the installed PreemptionGuard's handler runs
    # the actual signal path (not a shortcut into drain())
    if faults._active and faults.preempt_due(what or ""):
        os.kill(os.getpid(), _signal.SIGTERM)
    tok = next(_tokens)
    rec = {"token": tok, "owner_id": id(owner),
           "owner": weakref.ref(owner), "what": what or
           type(owner).__name__, "t0": time.monotonic(), "hung": None}
    with _lock:
        _inflight[tok] = rec
    return tok


def _hb_end(tok, exc):
    with _lock:
        rec = _inflight.pop(tok, None)
    if rec is None:
        return
    g = rec["hung"]
    if g is not None:
        g._on_hang_exit(rec, exc)


def thread_stacks(limit_frames: int = 10,
                  per_thread_chars: int = 1500) -> Dict[str, str]:
    """Per-thread stack snapshot (``sys._current_frames``), trimmed to
    the newest ``limit_frames`` frames — the forensic payload of a
    ``hang_suspected`` event."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        text = "".join(traceback.format_stack(frame)[-limit_frames:])
        out[f"{names.get(tid, 'thread')}:{tid}"] = \
            text[-per_thread_chars:]
    return out


def _owner_poison(owner) -> Optional[str]:
    """The owner's poison latch, whichever spelling it uses
    (``CompiledStep._poisoned`` / ``DataParallelTrainer.
    _donation_poisoned`` / ``Server._poisoned``)."""
    return getattr(owner, "_poisoned", None) or \
        getattr(owner, "_donation_poisoned", None)


class Guardian:
    """Hang watchdog for ONE step owner.

    Args:
      owner: a ``gluon.CompiledStep``, ``parallel.
        DataParallelTrainer``, or ``serving.Server`` (anything whose
        steps/dispatches open the ``telemetry.step_owner(owner, what)``
        heartbeat).  Held by weakref — a collected owner stops the
        watch.
      manager: the owner's ``CheckpointManager`` for the ``recover``
        action (omit for a ``Server``, whose ``recover()`` replays
        host-owned prompts instead of restoring a checkpoint).
      timeout: seconds in flight before a step is suspected hung
        (default ``MXTPU_WATCHDOG_TIMEOUT``).
      action: ``warn`` | ``dump`` | ``recover`` (default
        ``MXTPU_WATCHDOG_ACTION``) — the escalation ladder above.
      poll: watchdog scan period (default ``min(timeout / 4, 0.25)``).

    Use as a context manager or ``start()``/``stop()``.  The watchdog
    thread only OBSERVES; the recover escalation runs on the owning
    thread at the heartbeat's exit, so no cross-thread buffer races.
    """

    def __init__(self, owner, manager=None, timeout: float = None,
                 action: str = None, poll: float = None,
                 name: str = None):
        from .. import envs
        self.owner_ref = weakref.ref(owner)
        self.manager = manager
        self.timeout = float(envs.get("MXTPU_WATCHDOG_TIMEOUT")) \
            if timeout is None else float(timeout)
        if self.timeout <= 0:
            raise MXNetError(
                f"Guardian timeout must be > 0, got {self.timeout}")
        act = (action if action is not None
               else str(envs.get("MXTPU_WATCHDOG_ACTION"))).strip() \
            .lower()
        if act not in ("warn", "dump", "recover"):
            raise MXNetError(
                f"MXTPU_WATCHDOG_ACTION must be warn|dump|recover, "
                f"got {act!r}")
        self.action = act
        self.poll = max(0.005, float(poll) if poll is not None
                        else min(self.timeout / 4.0, 0.25))
        self.name = name or getattr(owner, "name",
                                    type(owner).__name__)
        self.hangs = 0
        self.recovered = 0
        self.last: Optional[dict] = None
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Guardian":
        if self._thread is not None:
            return self
        self._stop_ev.clear()
        _register(self)
        self._thread = threading.Thread(
            target=self._loop, name=f"mxtpu-guardian-{self.name}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop_ev.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        _unregister(self)

    def __enter__(self) -> "Guardian":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def report(self) -> dict:
        return {"name": self.name, "timeout": self.timeout,
                "action": self.action, "hangs": self.hangs,
                "recovered": self.recovered, "last": self.last}

    # -- watchdog ---------------------------------------------------------
    def _loop(self):
        while not self._stop_ev.wait(self.poll):
            owner = self.owner_ref()
            if owner is None:
                break            # owner collected: nothing to watch
            try:
                self._scan(id(owner))
            except Exception:
                pass             # the watchdog must never take down a job
        _unregister(self)

    def _scan(self, owner_id: int):
        now = time.monotonic()
        with _lock:
            # mark AND record the hang_suspected event while holding
            # the heartbeat lock: _hb_end blocks on it to pop the
            # record, so a dispatch resolving in this window is
            # guaranteed a LATER event seq for its hang_resolved /
            # recovery — the ordering MXL504's answered-check relies on
            due = [r for r in _inflight.values()
                   if r["owner_id"] == owner_id and r["hung"] is None
                   and now - r["t0"] > self.timeout]
            for r in due:
                r["hung"] = self
                self._suspect(r, now)
        # the flight-recorder artifact (file IO) happens OUTSIDE the
        # lock — it retains the event just recorded, and heartbeats
        # must not stall on the dump
        if due and self.action in ("dump", "recover"):
            from .. import telemetry
            try:
                path = telemetry.dump_flight_recorder(
                    reason=f"hang_suspected:{self.name}")
                if self.last is not None:
                    self.last["artifact"] = path
            except Exception:
                pass             # forensics must not mask the hang

    def _suspect(self, rec: dict, now: float):
        from .. import telemetry
        self.hangs += 1
        seconds = round(now - rec["t0"], 4)
        stacks = thread_stacks()
        telemetry.counter(
            "mxtpu_hangs_total",
            "dispatches suspected hung by the guardian watchdog").inc()
        telemetry.record_event(
            "hang_suspected", owner=self.name, what=rec["what"],
            seconds=seconds, timeout=self.timeout, action=self.action,
            stacks=stacks)
        self.last = {"what": rec["what"], "seconds": seconds,
                     "artifact": None}

    def _on_hang_exit(self, rec: dict, exc):
        """Owning-thread callback: the suspected-hung dispatch finally
        returned (or raised).  ``recover`` action + a poisoned owner →
        the PR 7 poison/recover protocol runs HERE, so the next step
        dispatches against healthy buffers."""
        from .. import telemetry
        owner = rec["owner"]()
        seconds = round(time.monotonic() - rec["t0"], 4)
        poison = _owner_poison(owner) if owner is not None else None
        recovered = False
        restored = None
        err = None
        if self.action == "recover" and owner is not None and \
                poison is not None:
            try:
                if self.manager is not None:
                    restored = owner.recover(self.manager)
                else:
                    restored = owner.recover()
                recovered = True
                self.recovered += 1
            except Exception as e:
                err = repr(e)[:300]
        telemetry.record_event(
            "hang_resolved", owner=self.name, what=rec["what"],
            seconds=seconds, poisoned=poison is not None,
            recovered=recovered, restored_step=restored,
            error=err or (repr(exc)[:300] if exc is not None else None))
        if self.last is not None:
            self.last.update(resolved_seconds=seconds,
                             recovered=recovered)


# -- preemption-safe drain ---------------------------------------------------

def drain_server(server, directory: str) -> dict:
    """Requeue every serving resident WITH its state recorded: live
    requests go back to the queue head (the documented replay-exact
    recovery path — prompts are host-owned) and the full queue —
    prompt, budget, temperature, eos, tokens generated so far — lands
    in ``serving-drain.json`` under ``directory`` so a RESTARTED
    process can resubmit them (:func:`restore_drained_requests`).
    Returns ``{"requeued", "queued", "manifest"}``."""
    from . import integrity as _integrity
    residents = server.sched.active_requests()
    queued = list(server.sched.queue)
    rows = []
    for req in residents + queued:
        rows.append({
            "prompt": [float(t) for t in req.prompt],
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "eos_id": req.eos_id,
            "generated": [int(t) for t in req.generated],
            # integrity row: restore_drained_requests refuses a
            # manifest whose token state rotted on disk — a corrupt
            # resident must replay LOUDLY, not decode garbage
            "sha256": _integrity.token_checksum(req.prompt,
                                                req.generated),
        })
    # reverse: evict(requeue=True) pushes to the queue HEAD, so
    # iterating backwards preserves the residents' relative order
    for req in reversed(residents):
        server.evict(req, reason="preempt_drain", requeue=True)
    manifest = {"format": 1, "kind": "mxtpu_serving_drain",
                "server": server.name, "requests": rows}
    path = os.path.join(directory, "serving-drain.json")
    tmp = path + f".tmp{os.getpid()}"
    os.makedirs(directory, exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, path)
    return {"requeued": len(residents), "queued": len(queued),
            "manifest": path}


def restore_drained_requests(server, path: str) -> list:
    """Resubmit every request a :func:`drain_server` manifest recorded
    (fresh-process restart leg).  Requests restart from their prompts —
    greedy replay reproduces the original stream token-for-token, the
    same recovery semantics ``Server.recover`` already proves.
    Deadlines are NOT re-applied (they dated the preempted process).
    Returns the new ``Request`` objects in manifest order."""
    import numpy as np
    from . import integrity as _integrity
    with open(path) as f:
        m = json.load(f)
    if m.get("kind") != "mxtpu_serving_drain" or m.get("format") != 1:
        raise MXNetError(f"{path} is not a serving drain manifest")
    rows = list(m.get("requests", ()))
    # validate EVERY checksum before the first submit: a rotten row
    # must not leave a partial restore behind (a retry after dropping
    # it would double-submit the rows that already landed)
    for i, row in enumerate(rows):
        want = row.get("sha256")
        if want is not None and want != _integrity.token_checksum(
                row["prompt"], row.get("generated", ())):
            # pre-checksum manifests (no sha256 row) restore as
            # before; a ROW THAT ROTTED refuses loudly — resubmitting
            # a silently-corrupt prompt would decode garbage with no
            # event anywhere
            raise MXNetError(
                f"serving drain manifest {path} row {i} failed its "
                "token checksum — the manifest is corrupt; drop the "
                "row or re-drain")
    out = []
    for row in rows:
        out.append(server.submit(
            np.asarray(row["prompt"], np.float32),
            max_new_tokens=int(row["max_new_tokens"]),
            temperature=float(row.get("temperature", 0.0)),
            eos_id=row.get("eos_id")))
    return out


class PreemptionGuard:
    """SIGTERM/SIGINT → drain to a committed boundary → exit 0.

    Args:
      manager: ``CheckpointManager`` (with its trainer attached) — the
        drain commits ``manager.save(block=True, force=True)``.
      server: optional ``serving.Server`` to drain (residents requeue
        + the replay manifest lands next to the checkpoint).
      deadline_s: drain budget (default ``MXTPU_DRAIN_DEADLINE_S``);
        overruns are recorded on the ``preempted`` event
        (``deadline_ok: false``), not enforced by interruption — a
        torn checkpoint would be worse than a late one.
      exit_process: ``os._exit(0)`` after a clean drain (production);
        ``False`` records the would-be code in ``exit_code`` instead
        (the in-process test/soak mode).
      signals: handled signal numbers (default SIGTERM + SIGINT).

    First signal: drain → exit 0.  Second signal while draining:
    dump forensics (flight recorder + stacks) → exit 1.  Install from
    the MAIN thread (CPython's ``signal.signal`` contract).
    """

    def __init__(self, manager=None, server=None,
                 deadline_s: float = None, exit_process: bool = True,
                 signals=None):
        from .. import envs
        if manager is None and server is None:
            raise MXNetError("PreemptionGuard needs a manager and/or "
                             "a server to drain")
        self.manager = manager
        self.server = server
        self.deadline_s = float(envs.get("MXTPU_DRAIN_DEADLINE_S")) \
            if deadline_s is None else float(deadline_s)
        self.exit_process = bool(exit_process)
        self.signals = tuple(signals) if signals is not None else \
            (_signal.SIGTERM, _signal.SIGINT)
        self._prev: Dict[int, object] = {}
        self._installed = False
        self._draining = False
        self.exit_code: Optional[int] = None
        self.drained: Optional[dict] = None

    # -- lifecycle --------------------------------------------------------
    def install(self) -> "PreemptionGuard":
        if self._installed:
            return self
        for sig in self.signals:
            self._prev[sig] = _signal.signal(sig, self._on_signal)
        self._installed = True
        _register(self)
        return self

    def uninstall(self):
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                _signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
        self._prev.clear()
        self._installed = False
        _unregister(self)

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()

    # -- the protocol -----------------------------------------------------
    def _on_signal(self, signum, frame):
        from .. import telemetry
        if self._draining:
            # second signal: the operator (or the scheduler's kill
            # escalation) wants OUT — dump forensics and force-exit
            try:
                telemetry.record_event("preempt_forced",
                                       signal=int(signum),
                                       stacks=thread_stacks())
                telemetry.dump_flight_recorder(reason="preempt_forced")
            except Exception:
                pass
            self._exit(1)
            return
        self._draining = True
        try:
            self.drain(signum=int(signum))
        except Exception as e:
            try:
                telemetry.record_event("preempted", ok=False,
                                       signal=int(signum),
                                       error=repr(e)[:300])
                telemetry.auto_dump(reason="preempt_drain_failed")
            except Exception:
                pass
            self._exit(1)
            return
        self._exit(0)

    def drain(self, signum: Optional[int] = None,
              reason: str = "signal") -> dict:
        """The drain state machine (callable directly for tests and
        orchestrators): in-flight step already finished (main-thread
        handler) → blocking force save to a committed boundary → drain
        the serving scheduler with a replay manifest → emit the
        retained ``preempted`` event + drain-duration histogram."""
        from .. import telemetry
        t0 = time.perf_counter()
        committed = None
        serving = None
        if self.manager is not None and self.manager.trainer is not None:
            committed = int(self.manager.save(block=True, force=True))
        if self.server is not None:
            if self.manager is not None:
                out_dir = self.manager.directory
            else:
                from .. import envs
                import tempfile
                out_dir = str(envs.get("MXTPU_TELEMETRY_EXPORT")
                              or "") or tempfile.gettempdir()
            serving = drain_server(self.server, out_dir)
        dt = time.perf_counter() - t0
        deadline_ok = dt <= self.deadline_s
        telemetry.counter(
            "mxtpu_preemptions_total",
            "preemption signals drained to a committed boundary").inc()
        telemetry.histogram(
            "mxtpu_drain_seconds",
            "preemption drain wall clock: signal -> committed "
            "boundary (s)").observe(dt)
        rec = {"reason": reason, "signal": signum,
               "committed_step": committed,
               "seconds": round(dt, 4),
               "deadline_s": self.deadline_s,
               "deadline_ok": deadline_ok}
        if serving is not None:
            rec.update(requeued=serving["requeued"],
                       queued=serving["queued"],
                       drain_manifest=serving["manifest"])
        telemetry.record_event("preempted", ok=True, **rec)
        if not deadline_ok:
            import warnings
            warnings.warn(
                f"preemption drain took {dt:.2f}s, over the "
                f"{self.deadline_s:.2f}s MXTPU_DRAIN_DEADLINE_S "
                "budget — the scheduler may have force-killed a real "
                "job here", RuntimeWarning, stacklevel=2)
        self.drained = rec
        return rec

    def _exit(self, code: int):
        self.exit_code = code
        if self.exit_process:
            # handlers run between bytecodes of arbitrary code;
            # sys.exit would be swallowed by bare except blocks —
            # preemption means GO, so hard-exit after flushing
            try:
                sys.stdout.flush()
                sys.stderr.flush()
            except Exception:
                pass
            os._exit(code)


def _reset():
    """Test hook: tear down every installed guardian plane and clear
    the heartbeat table."""
    for plane in list(_installed):
        try:
            if isinstance(plane, Guardian):
                plane.stop()
            else:
                plane.uninstall()
        except Exception:
            pass
    with _lock:
        _installed.clear()
        _inflight.clear()
        _sync_hook()
