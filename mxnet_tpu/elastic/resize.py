"""Live elastic resize: in-job mesh shrink/grow without a restart.

PR 7 made a mesh change survivable — at RESTART time: reshard +
re-AOT when the restored process's device count differs.  This module
removes the restart.  The array moves are the portable-collective
redistribution of arXiv 2112.01075 (``elastic.reshard``, the same
machinery the restore path uses) applied to the LIVE donated buffers,
and the executable swap rides the PR 5/10 AOT warm-start seam
(``engine.aot_compile`` / the persistent tier), so going from mesh A
to mesh B costs one drain + one dispatch swap — never a process
bounce, never a cold compile.

:class:`ResizeController` takes a running ``DataParallelTrainer``
through four phases (docs/elasticity.md, "Live resize"):

1. **pre-warm** — while the old mesh still trains,
   ``trainer.prepare_resize(mesh)`` AOT-compiles the step +
   ``step_multi(K)`` variants (and the ZeRO ``(dp, chunk)`` slice
   layout) for the target mesh;
2. **drain** — finish in-flight work and land on a COMMITTED
   checkpoint boundary (``manager.save(block=True)`` through the
   existing double-buffered device->host path) — the anchor every
   mid-resize crash heals from;
3. **reshard** — redistribute the live donated params / optimizer
   state / ZeRO slices (fp32-exact, donation-aware: the same-device-
   set move is ONE donated identity program, so there is never a
   transient 2x HBM copy of the model);
4. **swap + resume** — rebind the trainer's compiled entries and
   train on; downtime = drain start -> swap complete, measured into
   ``mxtpu_resize_downtime_seconds``.

Every transition has a deterministic fault point in the
``MXTPU_FAULT_INJECT`` grammar (``resize_drain`` / ``resize_prewarm``
/ ``resize_reshard`` / ``resize_swap``).  A fault before the drain
checkpoint commits aborts with the trainer untouched on the OLD mesh;
one after it crash-heals onto the NEW mesh by restoring the drain
checkpoint into the pre-warmed bindings (``recovery`` telemetry, as
in PR 7) — either way the trainer ends on a consistent mesh, never
poisoned with no recovery path.

The same protocol points at the serving plane:
:class:`ServingAutoscaler` watches the queue-depth / occupancy gauges
and drives ``serving.Server.resize_slots`` (prewarm -> drain ->
migrate -> swap) with hysteresis from the ``MXTPU_RESIZE_*`` knobs.

Every COMPLETED resize lands in an in-process registry
(:func:`resizes` / :func:`report`, rendered by ``tools/mxresize.py``)
that mxlint's MXL503 runtime pass audits: a resize whose first
post-swap step paid a fresh compile (pre-warm contract broken) or
whose drain committed an older step than the trainer had (a committed
step would be lost on heal) is a finding.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..base import MXNetError
from . import faults

__all__ = ["ResizeController", "ServingAutoscaler", "resizes",
           "report", "mesh_desc"]

_reg_lock = threading.Lock()
_records: List[dict] = []


def mesh_desc(mesh) -> Dict[str, int]:
    """``{axis: size}`` of a jax Mesh (registry/event field form)."""
    return {str(k): int(v) for k, v in mesh.shape.items()}


def _note_completed(rec: dict) -> dict:
    """Append a completed resize to the registry and emit the
    telemetry triple: counter, downtime histogram, retained ``resize``
    event."""
    from .. import telemetry
    with _reg_lock:
        _records.append(rec)
    telemetry.counter(
        "mxtpu_resizes_total",
        "completed live resizes (train mesh changes + serving slot "
        "changes), healed ones included").inc()
    telemetry.histogram(
        "mxtpu_resize_downtime_seconds",
        "drain start -> executable swap complete per live resize "
        "(s)").observe(float(rec.get("downtime_seconds", 0.0)))
    # the record's "kind" (train | serving) would collide with the
    # event taxonomy key — it rides as resize_kind in the event
    telemetry.record_event(
        "resize", **{("resize_kind" if k == "kind" else k): v
                     for k, v in rec.items() if not k.startswith("_")})
    return rec


def _note_failed(kind: str, phase: str, error: str, **fields):
    from .. import telemetry
    telemetry.record_event("resize_failed", resize_kind=kind,
                           phase=phase, error=error[:300], **fields)


def resizes() -> List[dict]:
    """Completed-resize records (oldest first; copies — the MXL503
    input).  ``post_swap_fresh_compiles`` stays ``None`` until the
    first post-swap step fires the trainer's one-shot probe."""
    with _reg_lock:
        return [dict(r) for r in _records]


def _reset():
    """Test hook."""
    with _reg_lock:
        _records.clear()


def report() -> dict:
    """Live-process resize report (``tools/mxresize.py status``)."""
    from .. import telemetry
    snap = telemetry.snapshot()
    hist = snap["histograms"].get("mxtpu_resize_downtime_seconds", {})
    return {
        "resizes": resizes(),
        "total": snap["counters"].get("mxtpu_resizes_total", 0.0),
        "downtime_seconds": {k: hist.get(k)
                             for k in ("count", "sum")},
        "failed_events": [e for e in telemetry.events("resize_failed")],
    }


def _trainer_step(trainer) -> int:
    opt = trainer.optimizer
    return int(max(opt._index_update_count.values(),
                   default=int(opt.num_update)))


class ResizeController:
    """Drive a running ``DataParallelTrainer`` from its mesh to a
    target mesh without losing a committed step.

    Args:
      trainer: a ``parallel.DataParallelTrainer`` with ``fuse_step=
        True`` that has run at least one fused step.
      manager: the trainer's ``elastic.CheckpointManager`` — the drain
        checkpoint (and any crash-heal) goes through it.
    """

    def __init__(self, trainer, manager):
        if manager is None:
            raise MXNetError(
                "ResizeController needs a CheckpointManager: the "
                "drain lands on a committed checkpoint boundary, and "
                "a mid-resize crash heals from it")
        self.trainer = trainer
        self.manager = manager

    def resize(self, mesh) -> dict:
        """Take the trainer to ``mesh`` — a jax Mesh, or a
        ``parallel.ShardingPlan`` for a plan-to-plan resize (target
        mesh from the plan's axes, target param layout from its rules;
        the swap adopts the plan).  Returns the registry record
        (also appended to :func:`resizes`).  A failure BEFORE the
        drain checkpoint commits raises with the trainer untouched on
        the old mesh; a failure after it heals onto the new mesh from
        the drain checkpoint (``healed: True`` in the record)."""
        from .. import engine, telemetry
        from ..parallel.planner import ShardingPlan
        trainer = self.trainer
        mesh_from = mesh_desc(trainer.mesh)
        mesh_to = dict(mesh.axes) if isinstance(mesh, ShardingPlan) \
            else mesh_desc(mesh)
        phase = "prewarm"
        try:
            # 1) PRE-WARM (the old mesh could still be stepping
            # between controller calls; nothing here touches it)
            faults.maybe_fire("resize_prewarm")
            t_pw = time.perf_counter()
            staged = trainer.prepare_resize(mesh)
            prewarm_s = time.perf_counter() - t_pw
            # 2) DRAIN — the downtime clock starts here: finish
            # in-flight checkpoint work and COMMIT the boundary the
            # swap (or a crash-heal) resumes from
            phase = "drain"
            t_drain = time.perf_counter()
            faults.maybe_fire("resize_drain")
            drain_step = _trainer_step(trainer)
            committed = int(self.manager.save(block=True, force=True))
        except Exception as e:
            # the trainer was never touched: still on mesh A, training
            _note_failed("train", phase, repr(e), mesh_from=mesh_from,
                         mesh_to=mesh_to, still_on="old_mesh")
            raise
        healed = False
        heal_error = None
        try:
            # 3) + 4) RESHARD + SWAP (fault points fire inside)
            trainer.apply_resize(staged)
        except Exception as e:
            # the drain checkpoint is committed and the mesh-B
            # programs are warm: adopt the new bindings and restore
            # the checkpoint INTO them — cleanly on mesh B, with the
            # PR 7 recovery telemetry
            heal_error = repr(e)
            _note_failed("train", "reshard_swap", heal_error,
                         mesh_from=mesh_from, mesh_to=mesh_to,
                         heal="checkpoint_restore")
            from .manager import timed_recover
            trainer._resize_swap(staged)
            timed_recover(self.manager, trainer, "resize_heal",
                          step=committed)
            trainer._note_resize_layouts()
            healed = True
        downtime = time.perf_counter() - t_drain
        rec = {
            "kind": "train", "mesh_from": mesh_from,
            "mesh_to": mesh_to, "zero_stage": trainer._zero_stage,
            "plan_to": trainer.plan.struct_hash()
            if getattr(trainer, "plan", None) is not None else None,
            "drain_step": drain_step, "committed_step": committed,
            "healed": healed,
            "prewarm_seconds": round(prewarm_s, 4),
            "downtime_seconds": round(downtime, 4),
            "post_swap_misses": None,
            "post_swap_fresh_compiles": None,
        }
        if heal_error:
            rec["heal_error"] = heal_error[:300]
        _note_completed(rec)
        # arm the pre-warm-contract probe: the FIRST post-swap step
        # finalizes the record with the compiles it paid (must be 0 —
        # MXL503 audits this).  The baseline is captured at that
        # step's START (trainer._note_resize_probe_base), not here:
        # the swap→first-step window is unbounded, and another owner
        # compiling in it must not be attributed to this resize.
        arm_counts = engine.compile_counts()
        t_swap = time.perf_counter()

        def _probe(base):
            m0, f0 = base if base is not None else arm_counts
            m1, f1 = engine.compile_counts()
            with _reg_lock:
                rec["post_swap_misses"] = m1 - m0
                rec["post_swap_fresh_compiles"] = f1 - f0
                rec["first_step_gap_seconds"] = round(
                    time.perf_counter() - t_swap, 4)

        trainer._post_resize_probe = _probe
        telemetry.record_event(
            "reshard", where="live_resize", saved_mesh=mesh_from,
            mesh=mesh_to)
        return dict(rec)


class ServingAutoscaler:
    """Hysteresis autoscale policy over the serving plane's existing
    signals (the ``mxtpu_serving_queue_depth`` /
    ``mxtpu_serving_batch_occupancy`` gauges' sources), driving
    ``Server.resize_slots`` through the same prewarm -> drain ->
    migrate -> swap protocol.

    Call :meth:`observe` once per scheduling round (or from a poll
    loop).  Growth doubles the slot count when the wait queue has been
    at/above ``up_queue`` for ``patience`` consecutive observations;
    shrink halves it when the queue is empty AND occupancy has been
    at/below ``down_occupancy`` for ``patience`` observations —
    asymmetric on purpose (grow on queued demand, shrink only when
    demonstrably idle).  ``cooldown_s`` spaces resizes so the two
    thresholds cannot flap the plane.  All defaults come from the
    ``MXTPU_RESIZE_*`` env knobs (docs/env_vars.md)."""

    def __init__(self, server, min_slots: Optional[int] = None,
                 max_slots: Optional[int] = None,
                 up_queue: Optional[int] = None,
                 down_occupancy: Optional[float] = None,
                 patience: Optional[int] = None,
                 cooldown_s: Optional[float] = None):
        from .. import envs

        def _get(v, name, typ):
            return typ(envs.get(name)) if v is None else typ(v)

        self.server = server
        self.min_slots = _get(min_slots, "MXTPU_RESIZE_MIN_SLOTS", int)
        self.max_slots = _get(max_slots, "MXTPU_RESIZE_MAX_SLOTS", int)
        self.up_queue = _get(up_queue, "MXTPU_RESIZE_UP_QUEUE", int)
        self.down_occupancy = _get(down_occupancy,
                                   "MXTPU_RESIZE_DOWN_OCCUPANCY",
                                   float)
        self.patience = max(1, _get(patience, "MXTPU_RESIZE_PATIENCE",
                                    int))
        self.cooldown_s = _get(cooldown_s, "MXTPU_RESIZE_COOLDOWN_S",
                               float)
        if self.min_slots < 1 or self.max_slots < self.min_slots:
            raise MXNetError(
                f"bad slot bounds [{self.min_slots}, "
                f"{self.max_slots}]")
        self._hot = 0
        self._cold = 0
        self._last_resize = float("-inf")

    def slots(self) -> int:
        return max(b.slots for b in self.server.sched.buckets)

    def observe(self) -> Optional[dict]:
        """One policy tick: update the hysteresis counters from the
        live queue depth / occupancy and fire a resize when a
        threshold held for ``patience`` ticks (and the cooldown
        passed).  Returns the resize record when one fired, else
        ``None``."""
        sched = self.server.sched
        q = sched.queue_depth()
        occ = sched.occupancy()
        if q >= self.up_queue:
            self._hot += 1
            self._cold = 0
        elif q == 0 and occ <= self.down_occupancy:
            self._cold += 1
            self._hot = 0
        else:
            self._hot = 0
            self._cold = 0
        now = time.monotonic()
        if now - self._last_resize < self.cooldown_s:
            return None
        cur = self.slots()
        target = None
        reason = None
        if self._hot >= self.patience and cur < self.max_slots:
            target = min(self.max_slots, cur * 2)
            reason = f"queue_depth {q} >= {self.up_queue}"
        elif self._cold >= self.patience and cur > self.min_slots:
            target = max(self.min_slots, cur // 2)
            reason = (f"occupancy {occ:.2f} <= "
                      f"{self.down_occupancy:.2f}, queue empty")
        if target is None or target == cur:
            return None
        self._hot = 0
        self._cold = 0
        self._last_resize = now
        return self.server.resize_slots(target, reason=reason)
