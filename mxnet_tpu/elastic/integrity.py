"""Silent-corruption sentry: in-graph integrity fingerprints,
cross-replica agreement audits, and quarantine-by-resize.

Every robustness plane so far reacts to LOUD failures — raises, hangs,
signals.  The scarier production failure is silent: a bit flips in one
dp replica's parameter buffer, a collective delivers a corrupt payload
on one link, a checkpoint shard rots on disk — and the job keeps
training wrong with no event.  This module makes corruption
*injectable* (the ``corrupt_param``/``corrupt_grad``/``corrupt_wire``
points of the ``MXTPU_FAULT_INJECT`` grammar), *detectable inside the
one-dispatch step*, and *healable* through the existing
checkpoint/resize machinery:

* **fingerprints** — a cheap per-replica bitwise fingerprint
  (:func:`fingerprint`: the uint32 wraparound sum of each tensor's bit
  pattern — a single bitflip changes it by ±2^b, which is never 0 mod
  2^32, so every single-bit flip is detected) of the step's input
  params and its post-collective gradients, computed INSIDE the same
  single donated dispatch under the health plane's existing
  ``lax.cond(due)`` sampling gate (``telemetry.health``), so the
  steady-state 1-dispatch/0-retrace contract holds and un-sampled
  steps pay nothing;
* **cross-replica agreement** — replicated values must agree across
  the dp axis: an ``all_gather`` of the per-replica fingerprints rides
  the health vector as ``(hi16, lo16)`` f32 slot pairs (exact — both
  halves are < 2^16), and the host sentinel flags any replica whose
  fingerprint differs from the MAJORITY value, *with device
  attribution*.  The corrupted replica is named, not hunted;
* **escalation** — an ``integrity_divergence`` anomaly joins the
  health sentinel's taxonomy with its own action ladder
  (``MXTPU_INTEGRITY_ACTION``): ``warn`` records the retained
  ``corruption_suspected`` event only; ``rollback`` restores the last
  committed checkpoint (the corrupt state is discarded — the PR 7
  protocol); ``quarantine`` additionally resizes the live trainer off
  the suspect device through :class:`~.resize.ResizeController` + the
  sharding planner (arXiv 2112.01075's portable redistribution used
  as an eviction move), emitting ``device_quarantined``;
* **checkpoint scrubbing** — ``CheckpointManager.scrub()``
  re-verifies committed shard sha256s in the background and
  quarantines rotten checkpoints so a restore can never serve them
  (:mod:`.manager`); the serving plane verifies KV-page checksums on
  migration and drain-manifest token hashes on restore
  (:func:`page_checksum`), so a corrupt resident replays loudly
  instead of decoding garbage.

The corruption points are deterministic under ``MXTPU_FAULT_SEED``:
``corrupt_param`` flips a bit in a chosen device's buffer of a live
replicated param (host-side — real physical state corruption);
``corrupt_grad``/``corrupt_wire`` bake a ctl-driven XOR into the
traced step (arming them retraces ONCE with attribution, exactly like
a health-config flip; production programs are byte-identical when no
drill is armed) so the detector is red→green testable on the tier-1
CPU mesh.  See docs/elasticity.md ("Integrity sentry").
"""
from __future__ import annotations

import hashlib
import math
import threading
from typing import List, Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["IntegritySpec", "enabled", "action", "trace_signature",
           "build_spec", "fingerprint", "body_rows", "jit_block",
           "ctl_vector", "corrupt_param_host", "agreement",
           "respond", "quarantine", "quarantine_mesh",
           "page_checksum", "token_checksum"]

#: bits above this stay clear of the f32 exponent/sign, so a
#: seeded-random ``corrupt_param`` flip perturbs the value without
#: manufacturing NaN/Inf (which the health plane's nonfinite detector
#: would catch FIRST and steal the attribution from the drill)
MAX_SAFE_BIT = 22


# -- configuration -----------------------------------------------------

def enabled() -> bool:
    """Is the integrity plane armed?  Rides the health plane (the
    fingerprints are extra slots of ITS vector, under ITS sampling
    gate) plus ``MXTPU_INTEGRITY``."""
    from ..telemetry import health as _health
    if not _health.enabled():
        return False
    from .. import envs
    return bool(envs.get("MXTPU_INTEGRITY"))


def action() -> str:
    """``warn`` | ``rollback`` | ``quarantine``
    (``MXTPU_INTEGRITY_ACTION``; unknown values degrade to warn)."""
    from .. import envs
    act = str(envs.get("MXTPU_INTEGRITY_ACTION")).strip().lower()
    return act if act in ("warn", "rollback", "quarantine") else "warn"


def trace_signature(mesh=None, dp_axis: Optional[str] = None,
                    grad_rows: bool = True) -> Optional[tuple]:
    """What the TRACED program bakes from this module: ``None`` when
    the plane is off or the mesh has no >1 dp axis (cross-replica
    agreement is vacuous — the program is then byte-identical to a
    pre-integrity build, and every pre-integrity persist hash still
    serves).  The step stacks fold this into their signature/persist
    identity next to ``health.trace_signature()`` so arming a
    corruption drill — which adds the ctl input and the XOR block —
    retraces once with attribution instead of mis-reading outputs."""
    if not enabled() or mesh is None or dp_axis is None:
        return None
    n_dp = int(dict(zip(mesh.axis_names,
                        mesh.devices.shape)).get(dp_axis, 1))
    if n_dp <= 1:
        return None
    from . import faults
    return ("integrity", 1, n_dp, bool(grad_rows),
            bool(faults.corrupt_armed()))


def struct_signature(grad_rows: bool = True) -> Optional[tuple]:
    """The MESH-INDEPENDENT integrity identity (``None`` when the
    plane is off): armed + grad-rows + inject, WITHOUT the dp size —
    the reshard warm-start path compares struct hashes across mesh
    sizes (a dp=1 save restoring onto dp=2 re-AOTs anyway; whether
    the fingerprint rows exist on the target is the target's own
    business, decided by its mesh)."""
    if not enabled():
        return None
    from . import faults
    return ("integrity", bool(grad_rows),
            bool(faults.corrupt_armed()))


class IntegritySpec:
    """Layout of the integrity slots appended to one owner's health
    vector: per-dp-replica uint32 fingerprints packed as ``(hi16,
    lo16)`` f32 pairs — params always, post-collective grads when
    ``grad_rows`` (ZeRO stage-2 never materializes a replicated
    gradient, so its spec drops the grad rows).  ``inject`` bakes the
    ctl-driven corruption block (drills only)."""

    __slots__ = ("n_dp", "grad_rows", "inject")

    def __init__(self, n_dp: int, grad_rows: bool = True,
                 inject: bool = False):
        if n_dp < 2:
            raise MXNetError(
                f"IntegritySpec needs a >1 dp axis, got {n_dp}")
        self.n_dp = int(n_dp)
        self.grad_rows = bool(grad_rows)
        self.inject = bool(inject)

    @property
    def kinds(self) -> Tuple[str, ...]:
        return ("param", "grad") if self.grad_rows else ("param",)

    @property
    def slots(self) -> int:
        return 2 * self.n_dp * len(self.kinds)

    def fields(self) -> List[str]:
        out = []
        for kind in self.kinds:
            out.extend(f"integrity.{kind}_fp_hi{i}"
                       for i in range(self.n_dp))
            out.extend(f"integrity.{kind}_fp_lo{i}"
                       for i in range(self.n_dp))
        return out

    def signature(self) -> tuple:
        return ("integrity", 1, self.n_dp, self.grad_rows, self.inject)

    def parse(self, tail) -> dict:
        """Recombine the f32 slot tail into per-replica uint32
        fingerprints: ``{"param_fp": [...], "grad_fp": [...]|None}``."""
        out = {"param_fp": None, "grad_fp": None}
        off = 0
        for kind in self.kinds:
            hi = tail[off:off + self.n_dp]
            lo = tail[off + self.n_dp:off + 2 * self.n_dp]
            out[f"{kind}_fp"] = [int(h) * 65536 + int(l)
                                 for h, l in zip(hi, lo)]
            off += 2 * self.n_dp
        return out


def build_spec(mesh, dp_axis: str,
               grad_rows: bool = True) -> Optional[IntegritySpec]:
    """The spec for one SPMD step owner, or ``None`` when the plane is
    off / the dp axis is not >1 (matches :func:`trace_signature`)."""
    sig = trace_signature(mesh, dp_axis, grad_rows)
    if sig is None:
        return None
    return IntegritySpec(sig[2], grad_rows=sig[3], inject=sig[4])


# -- traced computation ------------------------------------------------

def fingerprint(leaves):
    """uint32 wraparound sum of every leaf's bit pattern (one pass,
    no extra tensor materialized).  A single bitflip changes the sum
    by ±2^b (b < 32), never 0 mod 2^32 — every single-bit corruption
    is detected.  Leaves are viewed at f32 (a flip in a low-precision
    leaf changes its f32 image too)."""
    import jax.numpy as jnp
    from jax import lax
    total = jnp.uint32(0)
    for x in leaves:
        bits = lax.bitcast_convert_type(x.astype(jnp.float32),
                                        jnp.uint32)
        total = total + jnp.sum(bits.reshape(-1), dtype=jnp.uint32)
    return total


def _pack_rows(vecs):
    """``(n_dp,) uint32`` per kind -> one f32 vector of exact
    ``(hi16, lo16)`` halves (both < 2^16, exactly representable)."""
    import jax.numpy as jnp
    rows = []
    for vec in vecs:
        rows.append((vec >> 16).astype(jnp.float32))
        rows.append((vec & jnp.uint32(0xFFFF)).astype(jnp.float32))
    return jnp.concatenate(rows)


def _gather_rows(spec, dp_axis, other_axes, fams):
    """Per-device fingerprint scalars -> the packed slot rows with ONE
    all_gather: the kind fingerprints stack into a tiny ``(kinds,)``
    vector first (one psum lane, one gather lane — on a CPU mesh the
    collective COUNT, not the payload, is the cost)."""
    import jax.numpy as jnp
    from jax import lax
    fp = jnp.stack([fingerprint(f) for f in fams])     # (kinds,)
    for ax in (other_axes or ()):
        fp = lax.psum(fp, ax)
    mat = lax.all_gather(fp, dp_axis)                  # (n_dp, kinds)
    return _pack_rows([mat[:, k] for k in range(len(fams))])


def maybe_corrupt(spec: IntegritySpec, ictl, leaves, axis):
    """The in-graph corruption block (PER-DEVICE context — a shard_map
    body): XOR one bit into element 0 of leaf ``ictl[2]`` on the
    device whose dp index equals ``ictl[1]``.  ``ictl[0] <= 0`` is the
    exact identity (the XOR mask is 0), so an armed-but-idle drill
    step is bit-identical to an unarmed one."""
    import jax.numpy as jnp
    from jax import lax
    if spec is None or not spec.inject or ictl is None:
        return leaves
    dev = lax.axis_index(axis)
    armed = (ictl[0] > 0) & (dev == ictl[1].astype(jnp.int32))
    out = []
    for j, g in enumerate(leaves):
        bits = lax.bitcast_convert_type(g.astype(jnp.float32),
                                        jnp.uint32)
        flat = bits.reshape(-1)
        mask = jnp.where(
            armed & (ictl[2].astype(jnp.int32) == j),
            jnp.left_shift(jnp.uint32(1), ictl[3].astype(jnp.uint32)),
            jnp.uint32(0))
        flat = flat.at[0].set(flat[0] ^ mask)
        out.append(lax.bitcast_convert_type(
            flat.reshape(g.shape), jnp.float32).astype(g.dtype))
    return tuple(out)


def body_rows(spec: IntegritySpec, dp_axis: str, other_axes,
              param_leaves, grad_leaves, due=None):
    """The integrity slot rows, computed in a PER-DEVICE context (a
    shard_map body): local fingerprints, psum'd over any non-dp mesh
    axes (a tp-sharded layout contributes one fingerprint per dp
    REPLICA), all-gathered over dp, packed as f32 halves.  Gated on
    ``due`` exactly like the health reductions — un-sampled steps pay
    nothing and emit zero rows (all-zero rows parse as agreement)."""
    import jax.numpy as jnp
    from jax import lax
    if spec is None:
        return None

    def _rows():
        fams = [param_leaves] + \
            ([grad_leaves] if spec.grad_rows else [])
        return _gather_rows(spec, dp_axis, other_axes, fams)

    if due is None:
        return _rows()
    return lax.cond(due > 0, _rows,
                    lambda: jnp.zeros((spec.slots,), jnp.float32))


def jit_block(spec: IntegritySpec, mesh, dp_axis: str, param_leaves,
              grad_leaves, due=None, ictl=None):
    """The integrity block for a GLOBALLY-traced step body (the plain
    fused step, where no shard_map surrounds the caller): one inner
    shard_map computes the per-device rows — and, when a drill is
    armed, corrupts the gradients of the targeted device BEFORE they
    reach the optimizer update (the corruption enters the real
    dataflow; the same block's grad fingerprints detect it).

    Returns ``(grads, rows)`` — ``grads`` unchanged (and NOT routed
    through the block) when no drill is armed, so the production
    program carries only the sampled fingerprint reductions."""
    from jax.sharding import PartitionSpec as P
    from ..parallel._compat import shard_map
    if spec is None:
        return grad_leaves, None
    other = tuple(a for a in mesh.axis_names if a != dp_axis)
    n_p, n_g = len(param_leaves), len(grad_leaves)

    if spec.inject and ictl is not None:
        def body(ctl, *leaves):
            params = leaves[:n_p]
            grads = maybe_corrupt(spec, ctl, leaves[n_p:], dp_axis)
            return grads + (body_rows(spec, dp_axis, other, params,
                                      grads, due=None),)

        out = shard_map(
            body, mesh=mesh,
            in_specs=(P(),) * (1 + n_p + n_g),
            out_specs=(P(),) * (n_g + 1),
            check_vma=False)(ictl, *(tuple(param_leaves) +
                                     tuple(grad_leaves)))
        new_grads, rows = tuple(out[:n_g]), out[n_g]
        if due is not None:
            import jax.numpy as jnp
            from jax import lax
            rows = lax.cond(
                due > 0, lambda: rows,
                lambda: jnp.zeros((spec.slots,), jnp.float32))
        return new_grads, rows

    def body(*leaves):
        return body_rows(spec, dp_axis, other, leaves[:n_p],
                         leaves[n_p:], due=None)

    import jax.numpy as jnp
    from jax import lax

    def _rows():
        return shard_map(
            body, mesh=mesh, in_specs=(P(),) * (n_p + n_g),
            out_specs=P(), check_vma=False)(
                *(tuple(param_leaves) + tuple(grad_leaves)))

    rows = _rows() if due is None else lax.cond(
        due > 0, _rows,
        lambda: jnp.zeros((spec.slots,), jnp.float32))
    return grad_leaves, rows


# -- host side: drill plumbing ----------------------------------------

def ctl_vector(spec: Optional[IntegritySpec], n_leaves: int):
    """One step's corruption-ctl row ``[armed, device, leaf, bit]``
    (f32 (4,)): consults the ``corrupt_grad``/``corrupt_wire`` fault
    points and clamps the seeded payload to this owner's shape.  All
    zeros when nothing fires — the XOR block is then the identity."""
    import numpy as np
    out = np.zeros((4,), np.float32)
    if spec is None or not spec.inject:
        return out
    from . import faults
    point = "corrupt_grad"
    payload = faults.corrupt_due(point)
    if payload is None:
        point = "corrupt_wire"
        payload = faults.corrupt_due(point)
    if payload is None:
        return out
    out[0] = 1.0
    out[1] = float(int(payload["device"]) % spec.n_dp)
    out[2] = float(int(payload["leaf"]) % max(1, n_leaves))
    out[3] = float(int(payload["bit"]) % 32)
    faults.note_corruption_applied(
        point, device=int(out[1]), leaf=int(out[2]), bit=int(out[3]))
    return out


def corrupt_param_host(trainer, payload: dict) -> dict:
    """The ``corrupt_param`` drill: flip one bit in ONE device's local
    shard of a live replicated param buffer — real physical state
    corruption, exactly what a DRAM/HBM upset leaves behind.  The
    in-graph fingerprints see the divergent replica on the next
    sampled step, with the device attributed.  Deterministic under
    ``MXTPU_FAULT_SEED`` (the payload is drawn from the faults RNG).
    Returns the applied ``{device, leaf, bit, param}``."""
    import numpy as np
    import jax
    tr_idx = trainer._tr_idx
    j = int(payload["leaf"]) % len(tr_idx)
    p = trainer._params[tr_idx[j]]
    d = p.data()._data
    shards = list(d.addressable_shards)
    dev = int(payload["device"]) % len(shards)
    bit = int(payload["bit"]) % (MAX_SAFE_BIT + 1)
    hosts = [np.asarray(s.data).copy() for s in shards]
    flat = hosts[dev].reshape(-1)
    if flat.dtype != np.float32:
        raise MXNetError(
            f"corrupt_param targets f32 params; {p.name} is "
            f"{flat.dtype}")
    flat.view(np.uint32)[0] ^= np.uint32(1 << bit)
    arrs = [jax.device_put(h, s.device)
            for h, s in zip(hosts, shards)]
    p.data()._set_data(jax.make_array_from_single_device_arrays(
        d.shape, d.sharding, arrs))
    applied = {"device": dev, "leaf": j, "bit": bit, "param": p.name}
    from . import faults
    faults.note_corruption_applied("corrupt_param", **applied)
    return applied


# -- host side: agreement + escalation ---------------------------------

def agreement(fps: Sequence[int]) -> Optional[List[int]]:
    """Majority vote over per-replica fingerprints: ``None`` when all
    agree, else the MINORITY replica indices (the suspects).  An exact
    50/50 split names the higher-indexed half (arbitrary but
    deterministic — with 2 replicas there is no majority to trust)."""
    vals = list(fps)
    if len(set(vals)) <= 1:
        return None
    counts = {}
    for v in vals:
        counts[v] = counts.get(v, 0) + 1
    modal = sorted(counts.items(),
                   key=lambda kv: (-kv[1], vals.index(kv[0])))[0][0]
    return [i for i, v in enumerate(vals) if v != modal]


def note_suspected(where: str, row: str, suspects: List[int],
                   fps: Sequence[int], step: int) -> None:
    """The retained ``corruption_suspected`` event + counter — the
    flight-recorder row every escalation (and the MXL505 audit) hangs
    off."""
    from .. import telemetry
    telemetry.counter(
        "mxtpu_corruption_suspected_total",
        "cross-replica integrity divergences the sentry flagged").inc()
    telemetry.record_event(
        "corruption_suspected", where=where, row=row,
        suspects=[int(s) for s in suspects],
        fingerprints=[f"{int(v):08x}" for v in fps],
        step=int(step))


def respond(owner, verdict: dict) -> bool:
    """The action half of an ``integrity_divergence`` verdict
    (``MXTPU_INTEGRITY_ACTION``): ``warn`` records only (the
    ``corruption_suspected`` event already landed); ``rollback``
    restores the last committed checkpoint through the owner's
    ``recover(manager)``; ``quarantine`` additionally resizes the
    owner off the suspect device.  Returns True when a recovery
    action ran.  Missing manager degrades LOUDLY (a retained event),
    never crashes the training loop."""
    from .. import telemetry
    act = action()
    if act == "warn":
        return False
    manager = getattr(owner, "health_manager", None)
    if manager is None:
        telemetry.record_event(
            "health_anomaly", where="integrity",
            anomaly=f"{act}_unarmed",
            detail=f"MXTPU_INTEGRITY_ACTION={act} but no "
                   "health_manager is attached; set "
                   "owner.health_manager to a CheckpointManager")
        return False
    suspects = verdict.get("suspects") or []
    try:
        if act == "quarantine" and suspects:
            quarantine(owner, manager, int(suspects[0]))
        else:
            owner.recover(manager)
    except Exception as e:
        telemetry.record_event(
            "health_anomaly", where="integrity",
            anomaly=f"{act}_failed",
            detail=f"{act} on suspects {suspects} failed: "
                   f"{e!r}"[:300])
        return False
    telemetry.record_event("corruption_resolved", where="integrity",
                           action=act,
                           suspects=[int(s) for s in suspects],
                           step=int(verdict.get("step", 0)))
    return True


def quarantine_mesh(mesh, dp_axis: str, suspect: int,
                    new_dp: Optional[int] = None):
    """The resize target that EXCLUDES the suspect device: the
    remaining dp members, shrunk to ``new_dp`` (default: the largest
    power of two below the old size — power-of-two sizes keep the
    usual batch divisibility).  Only a pure-dp mesh can quarantine
    one device (a dp x tp mesh would have to drop a whole dp column —
    raise so the caller degrades to rollback)."""
    import numpy as np
    from ..parallel.mesh import make_mesh
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    n_dp = int(shape.get(dp_axis, 1))
    if len([a for a, s in shape.items() if s > 1]) > 1 or n_dp < 2:
        raise MXNetError(
            f"quarantine needs a pure-dp mesh with dp >= 2, got "
            f"{shape} (set MXTPU_INTEGRITY_ACTION=rollback for "
            "multi-axis meshes)")
    devs = [d for i, d in enumerate(np.asarray(
        mesh.devices).reshape(-1)) if i != (suspect % n_dp)]
    if new_dp is None:
        new_dp = 2 ** int(math.floor(math.log2(n_dp - 1)))
    new_dp = int(new_dp)
    if not 1 <= new_dp <= len(devs):
        raise MXNetError(
            f"quarantine target dp={new_dp} does not fit the "
            f"{len(devs)} remaining devices")
    return make_mesh({dp_axis: new_dp}, devices=devs)


def quarantine(owner, manager, suspect: int,
               new_dp: Optional[int] = None) -> dict:
    """Evict the suspect device from a live trainer: (1) roll back to
    the last committed checkpoint (the corrupt state is discarded —
    fp32-exact restore, PR 7), then (2) resize onto a mesh excluding
    the suspect through :class:`~.resize.ResizeController` (drain →
    reshard → pre-warmed swap, PR 11) — the arXiv 2112.01075
    redistribution used as an eviction move.  Emits the retained
    ``device_quarantined`` event + counter; returns the resize
    record."""
    import time
    from .. import telemetry
    from .resize import ResizeController
    t0 = time.perf_counter()
    qmesh = quarantine_mesh(owner.mesh, owner.dp_axis, suspect,
                            new_dp=new_dp)
    restored = owner.recover(manager)
    rec = ResizeController(owner, manager).resize(qmesh)
    telemetry.counter(
        "mxtpu_corruption_quarantines_total",
        "devices quarantined off a live mesh on an integrity "
        "verdict").inc()
    telemetry.record_event(
        "device_quarantined", where="integrity",
        suspect=int(suspect),
        restored_step=int(restored),
        mesh_to=rec.get("mesh_to"),
        seconds=round(time.perf_counter() - t0, 4))
    return rec


# -- checksums (checkpoint scrub + serving legs) -----------------------

def page_checksum(host) -> str:
    """sha256 (16 hex chars) of a host array's bytes — the KV-page /
    shard checksum shared by the serving migration verify and the
    drain-manifest rows."""
    import numpy as np
    a = np.ascontiguousarray(np.asarray(host))
    return hashlib.sha256(a.tobytes()).hexdigest()[:16]


def token_checksum(prompt, generated) -> str:
    """Checksum of one serving request's host-owned token state (the
    drain-manifest integrity row: a corrupt manifest replays loudly
    instead of decoding garbage)."""
    blob = ",".join(str(int(t)) for t in prompt) + "|" + \
        ",".join(str(int(t)) for t in generated)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# -- scrub bookkeeping (the MXL505 input) ------------------------------

import collections as _collections

_scrub_lock = threading.Lock()
#: bounded like the retained event ring — a background scrubber on a
#: long-lived job appends one verdict per committed checkpoint per
#: pass, and the MXL505 audit only needs the recent window
_scrub_log = _collections.deque(maxlen=512)


def note_scrub(row: dict) -> None:
    with _scrub_lock:
        _scrub_log.append(dict(row))


def scrub_log() -> List[dict]:
    """Per-checkpoint scrub verdicts of THIS process (oldest first;
    copies) — ``analyze_elasticity``'s MXL505 input."""
    with _scrub_lock:
        return [dict(r) for r in _scrub_log]


def _reset():
    """Test hook."""
    with _scrub_lock:
        _scrub_log.clear()
