"""``mxnet_tpu.elastic``: the fault-tolerance plane.

Production training dies three ways the hot path alone cannot answer:
a dispatch fails after its donated buffers were consumed (the trainer
used to be permanently poisoned), the process is preempted (nothing
durable existed to resume from), and a restart lands on a different
chip count (warm start used to hard-fail to a cold start).  This
package closes all three:

* :class:`CheckpointManager` (:mod:`.manager`) — atomic, async,
  integrity-checked checkpoints of params + optimizer state + RNG +
  step counters, with bounded retention;
* ``trainer.recover(manager)`` — a poisoned
  ``DataParallelTrainer``/``CompiledStep`` rebuilds its donated
  buffers from the last committed checkpoint and trains on;
* :mod:`.reshard` — checkpoint/live array redistribution across mesh
  changes (arXiv:2112.01075), so an 8-chip checkpoint restores onto 4
  chips (or 1) exactly;
* :mod:`.faults` — deterministic fault injection
  (``MXTPU_FAULT_INJECT``) hooked into the real dispatch and
  checkpoint-commit paths, so every recovery path above is exercised
  by the tier-1 CPU suite;
* :mod:`.guardian` — the hang watchdog (heartbeat-fed
  :class:`~.guardian.Guardian`) and the SIGTERM/SIGINT
  :class:`~.guardian.PreemptionGuard` drain-to-committed-boundary
  protocol;
* :mod:`.chaos` — the seeded chaos-soak certifier
  (:class:`~.chaos.Schedule` / :func:`~.chaos.soak`) that runs train +
  serve + resize under randomized composed faults and checks the
  recovery invariants after every transition;
* :mod:`.integrity` — the silent-corruption sentry: in-graph
  cross-replica fingerprint agreement with device attribution,
  seeded ``corrupt_*`` injection, quarantine-by-resize, and the
  checkpoint/serving checksum legs (docs/elasticity.md, "Integrity
  sentry").

See docs/elasticity.md.
"""
from __future__ import annotations

from . import faults
from . import reshard

__all__ = ["CheckpointManager", "Guardian", "PreemptionGuard",
           "ResizeController",
           "ServingAutoscaler", "chaos", "faults", "guardian",
           "integrity", "manager", "reshard", "resize"]


def __getattr__(name):
    # manager pulls in ndarray/telemetry; keep package import light so
    # engine can import .faults without a cycle (resize/guardian/chaos
    # ride the same lazy path — they reach into the trainers/serving
    # plane)
    if name in ("CheckpointManager", "manager"):
        import importlib
        mod = importlib.import_module(".manager", __name__)
        return mod if name == "manager" else mod.CheckpointManager
    if name in ("ResizeController", "ServingAutoscaler", "resize"):
        import importlib
        mod = importlib.import_module(".resize", __name__)
        return mod if name == "resize" else getattr(mod, name)
    if name in ("Guardian", "PreemptionGuard", "guardian"):
        import importlib
        mod = importlib.import_module(".guardian", __name__)
        return mod if name == "guardian" else getattr(mod, name)
    if name in ("chaos", "integrity"):
        import importlib
        return importlib.import_module("." + name, __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
