"""Chaos-soak certifier: randomized COMPOSED faults over the whole
recovery surface, with invariants checked after every transition.

Eleven PRs of recovery paths were each proven against a single
scripted fault at a known point.  Production dies differently: faults
land in combination — a hang while a checkpoint writes, a preemption
right after a rollback, a serving poison mid-resize.  This module is
the harness that certifies the COMPOSED surface:

* :class:`Schedule` — a seeded random fault plan over the
  ``elastic.faults`` grammar (dispatch / dispatch_post /
  dispatch_hang / nonfinite_grad / preempt_signal / checkpoint_write
  / host_copy / serving dispatch_post / resize_reshard), deterministic
  per seed (``MXTPU_FAULT_SEED`` by default) so every soak replays
  exactly;
* :func:`soak` — runs a real training loop (gluon ``CompiledStep`` +
  ``CheckpointManager`` + ``Guardian`` + ``PreemptionGuard`` +
  health-rollback), a live serving plane (tiny llama ``Server``), one
  in-job serving resize, and a 10x request flood, under the plan —
  and checks the invariants after every recovery:

  1. **committed-step monotonicity** — every recovery resumes a step
     that was committed at the time and never ahead of the trainer;
     the final trainer step reaches the target and is committed;
  2. **fp32-exact params** vs an unfaulted reference run at the same
     step count (recoveries replay, they do not drift);
  3. **zero fresh compiles once warmed** (the resize pre-warm window
     excepted) — recovery is a data operation, never a compile;
  4. **no poisoned-but-unrecovered owner** at exit;
  5. **no leaked live buffers** (``engine.cache_info()["live_bytes"]``
     returns to its warmed baseline).

Artifacts land in an in-process registry (:func:`artifacts`) audited
by mxlint MXL504 and are rendered/replayed by ``tools/mxsoak.py``.
See docs/elasticity.md ("Guardian & chaos soak").
"""
from __future__ import annotations

import gc
import json
import os
import random as _random
import tempfile
import threading
from typing import List, Optional

from ..base import MXNetError
from . import faults

__all__ = ["Schedule", "soak", "artifacts", "render",
           "CATALOG", "FORMAT"]

FORMAT = 1

#: the fault catalog the schedule draws from: (target, grammar point).
#: ``target`` picks the operation the spec is armed around — the plan
#: composes faults across train, checkpoint, serving, and resize.
CATALOG = (
    ("train", "dispatch"),          # transient: retry absorbs
    ("train", "dispatch_post"),     # poison -> recover(manager)
    ("train", "dispatch_hang"),     # watchdog -> hang_suspected -> recover
    ("train", "nonfinite_grad"),    # health rollback
    ("train", "preempt_signal"),    # SIGTERM -> drain -> restore
    ("save", "checkpoint_write"),   # torn write: previous stays
    ("save", "host_copy"),          # snapshot copy failure
    ("serve", "dispatch_post"),     # serving poison -> replay
    ("resize", "resize_reshard"),   # mid-resize crash-heal
)

_reg_lock = threading.Lock()
_artifacts: List[dict] = []


def artifacts() -> List[dict]:
    """Completed soak artifacts of THIS process (the MXL504 input)."""
    with _reg_lock:
        return [dict(a) for a in _artifacts]


def _register(artifact: dict):
    with _reg_lock:
        _artifacts.append(artifact)


def _reset():
    """Test hook."""
    with _reg_lock:
        _artifacts.clear()


class Schedule:
    """A seeded random fault plan: ``n_faults`` entries spread over
    ``steps`` train steps, covering at least ``min_points`` DISTINCT
    grammar points, plus one serving resize and one request-flood
    stage.  Deterministic: the same seed yields the same plan."""

    def __init__(self, seed: Optional[int] = None, steps: int = 200,
                 n_faults: int = 8, min_points: int = 6,
                 resize: bool = True, flood: bool = True):
        from .. import envs
        self.seed = int(envs.get("MXTPU_FAULT_SEED")) if seed is None \
            else int(seed)
        self.steps = int(steps)
        if self.steps < 20:
            raise MXNetError(
                f"a soak needs >= 20 steps, got {self.steps}")
        n_faults = int(n_faults)
        rng = _random.Random(self.seed)
        self.resize_at = (self.steps // 2) if resize else None
        self.flood_at = (self.steps * 3 // 4) if flood else None

        names = []
        seen = set()
        for _t, p in CATALOG:
            if p not in seen:
                seen.add(p)
                names.append(p)
        min_points = min(int(min_points), len(names), n_faults)
        # cover min_points DISTINCT grammar points first, then free
        # picks over the whole catalog (repeats welcome — composed
        # repetition is part of the chaos)
        chosen_points = rng.sample(names, min_points)
        picks = [next(c for c in CATALOG if c[1] == p)
                 for p in chosen_points]
        while len(picks) < n_faults:
            picks.append(CATALOG[rng.randrange(len(CATALOG))])
        # at most one resize fault: there is one resize to land it on
        resize_picks = [c for c in picks if c[0] == "resize"]
        if not resize:
            picks = [c for c in picks if c[0] != "resize"]
        elif len(resize_picks) > 1:
            keep = resize_picks[0]
            picks = [c for c in picks if c[0] != "resize"]
            picks.append(keep)
        rng.shuffle(picks)
        # unique fault steps, clear of the warm-up and the final drain
        lo, hi = 3, max(4, self.steps - 2)
        steps_pool = list(range(lo, hi))
        rng.shuffle(steps_pool)
        self.entries: List[dict] = []
        for (target, point), at in zip(picks, steps_pool):
            if target == "resize":
                at = self.resize_at
            self.entries.append({"step": int(at), "target": target,
                                 "point": point})
        self.entries.sort(key=lambda e: e["step"])

    def distinct_points(self) -> int:
        return len({e["point"] for e in self.entries})

    def to_dict(self) -> dict:
        return {"seed": self.seed, "steps": self.steps,
                "resize_at": self.resize_at, "flood_at": self.flood_at,
                "entries": [dict(e) for e in self.entries]}

    def describe(self) -> str:
        lines = [f"chaos plan: seed {self.seed}, {self.steps} steps, "
                 f"{len(self.entries)} faults over "
                 f"{self.distinct_points()} distinct points"]
        for e in self.entries:
            lines.append(f"  step {e['step']:>4}  [{e['target']:>6}] "
                         f"{e['point']}")
        if self.resize_at is not None:
            lines.append(f"  step {self.resize_at:>4}  [serve ] "
                         "resize_slots x2")
        if self.flood_at is not None:
            lines.append(f"  step {self.flood_at:>4}  [serve ] "
                         "10x request flood (ttl-armed)")
        return "\n".join(lines)


def _owner_step(cs) -> int:
    """The gluon trainer's optimizer step counter (what checkpoints
    record as ``step``)."""
    opt = cs.trainer._optimizer
    return int(max(opt._index_update_count.values(),
                   default=int(opt.num_update)))


_ENV_PINS = {
    # pre-donation transients must be absorbed transparently (the
    # dispatch fault / a retried hang window), and quickly
    "MXTPU_DISPATCH_RETRIES": "2",
    "MXTPU_DISPATCH_BACKOFF_MS": "1",
    # the health plane detects nonfinite_grad EVERY step and closes
    # the loop with an automatic rollback into the manager
    "MXTPU_HEALTH": "1",
    "MXTPU_HEALTH_EVERY": "1",
    "MXTPU_HEALTH_ACTION": "rollback",
}


def soak(steps: int = 200, schedule: Optional[Schedule] = None,
         seed: Optional[int] = None, serve_every: int = 5,
         save_every: int = 10, hang_ms: int = 150,
         watchdog_timeout: float = 0.06,
         out_dir: Optional[str] = None,
         progress=None, sanitize: bool = True) -> dict:
    """Run the chaos soak and return its artifact (also appended to
    :func:`artifacts` for the MXL504 audit; written to
    ``out_dir/soak-<seed>.json`` when ``out_dir`` is given).

    The workload: a gluon ``CompiledStep`` MLP trainer stepping a
    deterministic per-step batch stream to ``steps`` optimizer steps
    (checkpointed every ``save_every``), a tiny-llama serving plane
    taking one request every ``serve_every`` steps, ONE in-job serving
    resize (slot count x2) at mid-soak, and a ttl-armed 10x flood at
    3/4 — all under ``schedule`` (default: ``Schedule(seed, steps)``).
    ``progress``: optional callable taking one status line.

    ``sanitize`` (default on): arm mxsan (``analysis.sanitizer``) for
    the soak's duration, so every fault/recovery/resize transition
    runs under the donation-lifetime checker and the lock-order
    graph; an MXL70x violation recorded during the soak fails the
    ``sanitizer_clean`` invariant — a soak that passes the recovery
    invariants but trips the sanitizer does NOT certify.
    """
    import numpy as np
    sched = schedule if schedule is not None else \
        Schedule(seed=seed, steps=steps)
    steps = sched.steps
    say = progress if callable(progress) else (lambda s: None)

    import mxnet_tpu as mx
    from .. import engine, nd, telemetry
    from ..gluon import Trainer, nn
    from ..gluon.compiled_step import CompiledStep
    from ..gluon.loss import L2Loss
    from ..models import LlamaForCausalLM, llama_tiny
    from ..serving import Server
    from .guardian import Guardian, PreemptionGuard
    from .manager import CheckpointManager

    V = 47                                   # serving vocab

    def _build(prefix):
        mx.random.seed(123)
        np.random.seed(7)
        net = nn.HybridSequential(prefix=prefix)
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu", in_units=8),
                    nn.Dense(4, in_units=16))
        net.initialize(mx.init.Xavier(), ctx=mx.cpu(0))
        tr = Trainer(net.collect_params(), "adam",
                     {"learning_rate": 0.01}, kvstore=None)
        return net, CompiledStep(net, L2Loss(), tr)

    def _batch(i):
        r = np.random.RandomState(10_000 + i)
        return (nd.array(r.rand(8, 8).astype("f4")),
                nd.array(r.rand(8, 4).astype("f4")))

    def _prompt(i):
        return np.random.RandomState(50_000 + i) \
            .randint(0, V, 5).astype("f4")

    env_prev = {k: os.environ.get(k) for k in _ENV_PINS}
    os.environ.update(_ENV_PINS)
    faults.clear()
    # mxsan armed mode: level 1 (collect — the soak's own poison/
    # recover drills must run their natural course; level 2 would
    # preempt them) unless the process already runs hotter.  The
    # per-key COUNTS are snapshotted (not the list length: records
    # dedup by (rule, key), so a repeat of a pre-soak violation only
    # bumps a count) so exactly the violations recorded DURING the
    # soak fail certification.
    from ..analysis import sanitizer as _san
    san_prev = _san.level()
    san_base_prev = _san.baseline()
    san_counts0: dict = {}
    if sanitize:
        _san.configure(max(san_prev, 1))
        san_counts0 = {(r["rule"], r["location"]): r["count"]
                       for r in _san.records()}
    # a soak is a DRILL: its injected poisons/errors must not consume
    # the process's throttled crash-forensics budget (a real failure
    # after the soak still deserves its auto-dump)
    from ..telemetry import recorder as _recorder
    dumps_prev = _recorder._auto_dumps_left
    ckdir = tempfile.mkdtemp(prefix="mxtpu-soak-")
    guard = pguard = mgr = None
    violations: List[dict] = []
    fired: List[dict] = []
    commits: List[int] = []
    recoveries: List[dict] = []
    preemptions = 0
    flood_stats = None
    resize_rec = None
    resize_fresh = 0

    def _violate(invariant, detail):
        violations.append({"invariant": invariant, "detail": detail})

    try:
        net, cs = _build("soak_")
        mgr = CheckpointManager(ckdir, trainer=cs, keep=4,
                                async_save=False)
        cs.health_manager = mgr                 # arms rollback
        mx.random.seed(321)
        np.random.seed(11)
        lm = LlamaForCausalLM(llama_tiny(vocab_size=V))
        lm.initialize(mx.init.Xavier())
        srv = Server(lm, buckets=[(2, 8)], max_new_tokens=4,
                     max_queue=256)
        guard = Guardian(cs, mgr, timeout=watchdog_timeout,
                         action="recover", name="soak_train").start()
        pguard = PreemptionGuard(manager=mgr, server=srv,
                                 exit_process=False)
        pguard.install()

        # -- warm-up: pay every compile the steady state will use ----
        commits.append(mgr.save(block=True))            # step-0 anchor
        cs.step(*_batch(1), 8)
        commits.append(mgr.save(block=True))            # snapshot warm
        stream: List = [srv.submit(_prompt(0)), srv.submit(_prompt(1))]
        srv.run()
        mx.nd.waitall()
        gc.collect()
        live0 = engine.cache_info()["live_bytes"]
        _m0, fresh0 = engine.compile_counts()
        if sanitize:
            # the MXL704 leak baseline = the same warmed census the
            # no_leaked_buffers invariant anchors on
            _san.mark_baseline(live0)
        say(f"warmed: live {live0} B, plan\n{sched.describe()}")

        rec_seen = len(telemetry.events("recovery"))
        cur = 1
        iter_n = 0
        max_iters = steps * 4 + 200
        pending = [dict(e) for e in sched.entries]
        resize_done = sched.resize_at is None
        flood_done = sched.flood_at is None

        def _due(target):
            out = [e for e in pending
                   if e["target"] == target and e["step"] <= cur + 1]
            for e in out:
                pending.remove(e)
            return out

        def _arm(entries):
            specs = []
            for e in entries:
                spec = e["point"]
                if e["point"] == "dispatch_hang":
                    spec += f":ms={int(hang_ms)}"
                specs.append(spec)
            if specs:
                faults.configure(";".join(specs), seed=sched.seed)
            return bool(specs)

        def _reap(_entries):
            for rep in faults.fired():
                fired.append({"step": cur + 1, "spec": rep})
            faults.clear()

        while cur < steps and iter_n < max_iters:
            iter_n += 1
            before = cur
            ent = _due("train")
            armed = _arm(ent)
            step_err = None
            try:
                cs.step(*_batch(cur + 1), 8)
            except Exception as e:
                step_err = e
            finally:
                if armed:
                    _reap(ent)
            # reconcile the step counter with what actually happened:
            # recoveries (guardian hang-recover, health rollback,
            # explicit poison recover below) rewind to a committed step
            if cs._poisoned is not None:
                # escalation that nobody auto-recovered (warn/dump
                # action would land here) — the soak recovers itself
                cs.recover(mgr)
            recov = telemetry.events("recovery")
            new_rec = recov[rec_seen:]
            rec_seen = len(recov)
            # only TRAINER recoveries move the train step counter —
            # serving/resize recoveries carry no restored step (their
            # event 'step' field is the global telemetry counter)
            train_rec = [e for e in new_rec
                         if e.get("where") == "compiled_step"]
            if new_rec:
                for ev in new_rec:
                    recoveries.append({
                        "where": ev.get("where"),
                        "step": ev.get("step")
                        if ev in train_rec else None,
                        "seconds": ev.get("seconds")})
            if train_rec:
                for ev in train_rec:
                    rstep = ev.get("step")
                    if rstep is None:
                        continue
                    if rstep > before + 1:
                        _violate("committed_monotonic",
                                 f"recovery resumed step {rstep} "
                                 f"ahead of trainer step "
                                 f"{before + 1}")
                    if rstep not in commits:
                        _violate("committed_monotonic",
                                 f"recovery resumed step {rstep} "
                                 "which was never committed "
                                 f"(commits: {sorted(set(commits))})")
                cur = _owner_step(cs)
            elif pguard.drained is not None:
                # a preemption drill drained mid-step: simulate the
                # restart leg — restore the drain checkpoint and
                # continue from it (serving residents were requeued
                # with state by the drain itself)
                preemptions += 1
                d = pguard.drained
                pguard.drained = None
                pguard._draining = False
                pguard.exit_code = None
                restored = mgr.restore(step=d["committed_step"])
                commits.append(int(d["committed_step"]))
                cur = _owner_step(cs)
                if restored != d["committed_step"]:
                    _violate("committed_monotonic",
                             f"drain committed {d['committed_step']} "
                             f"but restore served {restored}")
                say(f"preempted at step {before + 1}: drained to "
                    f"{d['committed_step']} in {d['seconds']}s")
            elif step_err is not None:
                _violate("no_unrecovered_poison",
                         f"step {before + 1} failed without a "
                         f"recovery path: {step_err!r}")
                break
            else:
                cur += 1

            # periodic committed boundary (with save-targeted faults)
            if cur % save_every == 0 and step_err is None \
                    and not train_rec:
                ent = _due("save")
                armed = _arm(ent)
                try:
                    commits.append(mgr.save(block=True, force=True))
                except faults.FaultError:
                    pass    # torn write: previous commit authoritative
                finally:
                    if armed:
                        _reap(ent)

            # serving stream: one request, served to completion (the
            # per-round drain keeps the stream sustainable, so the
            # flood stage below measures the OVERLOAD policy and not
            # a backlog this loop created)
            if iter_n % serve_every == 0:
                ent = _due("serve")
                armed = _arm(ent)
                try:
                    stream.append(srv.submit(_prompt(len(stream))))
                    srv.run()
                except MXNetError:
                    srv.recover()       # poisoned pool: replay
                    srv.run()
                finally:
                    if armed:
                        _reap(ent)

            # one in-job resize, slot count x2 (+ optional fault)
            if not resize_done and cur >= sched.resize_at:
                resize_done = True
                ent = _due("resize")
                armed = _arm(ent)
                _m, f_before = engine.compile_counts()
                try:
                    resize_rec = srv.resize_slots(4, reason="chaos")
                except (MXNetError, faults.FaultError):
                    # pre-drain abort leaves the old config intact —
                    # retry without the fault (the documented abort
                    # semantics)
                    faults.clear()
                    resize_rec = srv.resize_slots(4, reason="chaos")
                finally:
                    if armed:
                        _reap(ent)
                resize_fresh = engine.compile_counts()[1] - f_before
                say(f"resize at step {cur}: {resize_rec['slots_from']}"
                    f" -> {resize_rec['slots_to']} slots, healed="
                    f"{resize_rec['healed']}")

            # the flood stage: 10x slot capacity, ttl-armed
            if not flood_done and cur >= sched.flood_at:
                flood_done = True
                slots = sum(b.slots for b in srv.sched.buckets)
                n = 10 * slots
                shed0 = telemetry.counter(
                    "mxtpu_requests_shed_total",
                    "requests shed at enqueue by the overload policy"
                    ).value
                admitted = 0
                for i in range(n):
                    try:
                        srv.submit(_prompt(90_000 + i), ttl_ms=40.0)
                        admitted += 1
                    except MXNetError:
                        pass
                for _ in range(4):
                    srv.step()
                shed = telemetry.counter(
                    "mxtpu_requests_shed_total",
                    "requests shed at enqueue by the overload policy"
                    ).value - shed0
                flood_stats = {
                    "submitted": n, "admitted": admitted,
                    "shed": int(shed),
                    "shed_rate": round(shed / n, 4),
                    "queue_after": srv.sched.queue_depth()}
                say(f"flood at step {cur}: {n} submits, "
                    f"{int(shed)} shed, queue "
                    f"{srv.sched.queue_depth()}")

        if cur < steps:
            _violate("committed_monotonic",
                     f"soak did not converge: reached step {cur} of "
                     f"{steps} in {iter_n} iterations")

        # -- final boundary + serving drain --------------------------
        final_commit = mgr.save(block=True, force=True)
        commits.append(final_commit)
        # integrity scrub over everything the soak committed: after
        # the composed fault plan (torn writes, host-copy failures,
        # rollback forks) every SURVIVING committed checkpoint must
        # still verify — a rotten one would make the recovery anchors
        # this whole certification rests on a lie
        scrub_rep = mgr.scrub(quarantine=False)
        if scrub_rep["corrupt"]:
            _violate("committed_monotonic",
                     f"scrub found {scrub_rep['corrupt']} corrupt "
                     f"committed checkpoint(s): "
                     f"{[r['step'] for r in scrub_rep['rows'] if not r['ok']]}")
        try:
            srv.run()
        except MXNetError:
            srv.recover()
            srv.run()
        mx.nd.waitall()
        _m1, fresh1 = engine.compile_counts()

        # -- invariants ----------------------------------------------
        if final_commit != steps:
            _violate("committed_monotonic",
                     f"final commit {final_commit} != target {steps}")

        steady_fresh = (fresh1 - fresh0) - resize_fresh
        if steady_fresh != 0:
            _violate("zero_fresh_compiles",
                     f"{steady_fresh} fresh compile(s) outside the "
                     "resize pre-warm window")

        if cs._poisoned is not None:
            _violate("no_unrecovered_poison",
                     f"trainer still poisoned: {cs._poisoned}")
        if srv._poisoned is not None:
            _violate("no_unrecovered_poison",
                     f"server still poisoned: {srv._poisoned}")
        not_done = [r.id for r in stream if r.state != "done"]
        if not_done:
            _violate("no_unrecovered_poison",
                     f"stream requests never completed: {not_done}")

        gc.collect()
        live1 = engine.cache_info()["live_bytes"]
        if live1 > live0 * 2 + (2 << 20):
            _violate("no_leaked_buffers",
                     f"live bytes grew {live0} -> {live1}")

        # fp32-exact parity vs the unfaulted reference at the same
        # step count (recoveries replay — they must not drift)
        ref_net, ref_cs = _build("soakref_")
        for i in range(1, steps + 1):
            ref_cs.step(*_batch(i), 8)
        mx.nd.waitall()
        mism = []
        want = {n_: p.data().asnumpy()
                for n_, p in ref_net.collect_params().items()}
        got = {n_: p.data().asnumpy()
               for n_, p in net.collect_params().items()}
        for (ka, va), (kb, vb) in zip(sorted(want.items()),
                                      sorted(got.items())):
            if not np.array_equal(va, vb):
                mism.append(ka)
        if mism:
            _violate("params_exact",
                     f"params differ from the unfaulted reference at "
                     f"step {steps}: {mism}")

        # mxsan certification leg: an MXL70x recorded during the soak
        # (use-after-donate, double donation, poisoned-step, leak,
        # lock cycle, lock-across-dispatch) fails certification even
        # when every recovery invariant held
        san_block = None
        if sanitize:
            _san.leak_check()
            san_new = [
                r for r in _san.records()
                if r["count"] > san_counts0.get(
                    (r["rule"], r["location"]), 0)]
            for r in san_new:
                _violate("sanitizer_clean",
                         f"{r['rule']}: {r['message'][:200]}")
            san_block = {
                "armed": True, "level": _san.level(),
                "locks_instrumented":
                    len(_san.instrumented_locks()),
                "lock_edges": len(_san.lock_graph()["edges"]),
                "violations": [
                    {"rule": r["rule"], "count": r["count"],
                     "message": r["message"][:200]}
                    for r in san_new],
            }

        inv_names = ["committed_monotonic", "params_exact",
                     "zero_fresh_compiles", "no_unrecovered_poison",
                     "no_leaked_buffers"]
        if sanitize:
            inv_names.append("sanitizer_clean")
        inv = {}
        for name in inv_names:
            bad = [v for v in violations if v["invariant"] == name]
            inv[name] = {"ok": not bad,
                         "violations": [v["detail"] for v in bad]}

        artifact = {
            "format": FORMAT, "kind": "mxtpu_chaos_soak",
            "seed": sched.seed, "steps": steps,
            "plan": sched.to_dict(),
            "faults_fired": fired,
            "n_faults": len(fired),
            "distinct_points": len(
                {f["spec"].split(":")[0] for f in fired}),
            "recoveries": recoveries,
            "n_recoveries": len(recoveries),
            "preemptions": preemptions,
            "commits": sorted(set(commits)),
            "scrub": {"checked": scrub_rep["checked"],
                      "corrupt": scrub_rep["corrupt"]},
            "resize": resize_rec,
            "flood": flood_stats,
            "serving_stats": srv.stats(),
            "sanitizer": san_block,
            "live_bytes": {"warm": live0, "end": live1},
            "invariants": inv,
            "violations": violations,
            "ok": not violations,
            "iterations": iter_n,
        }
        _register(artifact)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir, f"soak-{sched.seed}.json")
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(artifact, f, indent=1)
            os.replace(tmp, path)
            artifact["artifact_path"] = path
        return artifact
    finally:
        faults.clear()
        if sanitize:
            _san.configure(san_prev)
            # the MXL704 baseline was anchored at the soak's own small
            # warmed census — restore the caller's (a later
            # self_check() against the soak's baseline would report a
            # spurious leak for any bigger workload)
            _san._baseline_bytes = san_base_prev
        with _recorder._lock:
            _recorder._auto_dumps_left = dumps_prev
        if guard is not None:
            guard.stop()
        if pguard is not None:
            pguard.uninstall()
        if mgr is not None:
            mgr.close()
        import shutil
        shutil.rmtree(ckdir, ignore_errors=True)
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def render(artifact: dict) -> str:
    """Text rendering of a soak artifact (``tools/mxsoak.py
    render``); raises for malformed input so the CLI can exit 1."""
    if not isinstance(artifact, dict) or \
            artifact.get("kind") != "mxtpu_chaos_soak":
        raise ValueError("not an mxtpu_chaos_soak artifact")
    lines = [
        f"chaos soak: seed {artifact['seed']}, "
        f"{artifact['steps']} steps — "
        + ("ALL INVARIANTS HELD" if artifact.get("ok")
           else f"{len(artifact.get('violations') or [])} "
                "VIOLATION(S)")]
    lines.append(
        f"  faults fired: {artifact.get('n_faults')} across "
        f"{artifact.get('distinct_points')} distinct points; "
        f"recoveries: {artifact.get('n_recoveries')}; "
        f"preemptions: {artifact.get('preemptions')}")
    for f in artifact.get("faults_fired", ()):
        lines.append(f"    step {f.get('step'):>4}  {f.get('spec')}")
    sc = artifact.get("scrub")
    if sc:
        lines.append(
            f"  scrub: {sc.get('checked')} committed checkpoint(s) "
            f"re-verified, {sc.get('corrupt')} corrupt")
    rz = artifact.get("resize")
    if rz:
        lines.append(
            f"  resize: slots {rz.get('slots_from')} -> "
            f"{rz.get('slots_to')}, migrated {rz.get('migrated')}, "
            f"requeued {rz.get('requeued')}, healed "
            f"{rz.get('healed')}")
    fl = artifact.get("flood")
    if fl:
        lines.append(
            f"  flood: {fl.get('submitted')} submits, "
            f"{fl.get('shed')} shed "
            f"(rate {fl.get('shed_rate')}), queue after "
            f"{fl.get('queue_after')}")
    sb = artifact.get("sanitizer")
    if sb:
        lines.append(
            f"  sanitizer: armed (level {sb.get('level')}), "
            f"{sb.get('locks_instrumented')} locks instrumented, "
            f"{len(sb.get('violations') or ())} MXL70x violation(s)")
        for v in sb.get("violations", ()):
            lines.append(f"    {v.get('rule')} x{v.get('count')}: "
                         f"{v.get('message')}")
    for name, st in (artifact.get("invariants") or {}).items():
        mark = "OK " if st.get("ok") else "FAIL"
        lines.append(f"  [{mark}] {name}")
        for v in st.get("violations", ()):
            lines.append(f"         {v}")
    return "\n".join(lines)
