"""Atomic async sharded checkpointing (the elastic training plane).

``checkpoint.py``'s host-gather shim had no atomicity, no integrity,
and no recovery story: a crash mid-write left garbage a later load
would unpickle, and a poisoned trainer had nothing to restore from.
This manager is the durable leg of the poison/recover protocol:

* **snapshot without blocking the step loop** — ``save()`` takes
  device-side copies of params + optimizer state (cheap async
  dispatches that decouple the snapshot from the NEXT step's buffer
  donation), then a single background writer thread performs the
  device→host gather and the file writes (double-buffered: at most one
  write in flight; a second ``save()`` drains the previous one first,
  so at most two snapshots are ever alive);
* **atomic commit** — everything lands in ``.tmp-step-N-pid/`` and one
  ``os.rename`` publishes ``step-N/``; a crash at ANY point leaves the
  previous checkpoint authoritative and the torn temp dir visible to
  ``tools/mxckpt.py`` (``ls`` flags it, ``prune`` removes it);
* **integrity** — one ``.npy`` shard per tensor with its sha256 in the
  manifest; ``restore``/``verify`` recompute hashes and refuse partial
  or corrupt checkpoints with a clear ``MXNetError`` instead of
  loading garbage;
* **everything a resume needs** — params (incl. BatchNorm running
  stats), optimizer-state leaves, error-feedback residuals, optimizer
  update counts, the global RNG stream, the mesh axes + per-param
  sharding specs, and the warm-start persist identity, so a restart
  resumes bit-identical (MLP) / 1-2 ulp (fused reductions) and a
  mesh-size change restores through :mod:`..elastic.reshard`;
* **bounded retention** — the newest ``keep`` committed checkpoints
  survive (``MXTPU_CHECKPOINT_KEEP`` default).

See docs/elasticity.md for the on-disk format and the recovery
walkthrough; fault points ``host_copy`` / ``checkpoint_write`` (module
:mod:`.faults`) fire inside this writer so tier-1 exercises every
crash window.
"""
from __future__ import annotations

import glob as _glob
import hashlib
import io
import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..base import MXNetError
from . import faults

__all__ = ["CheckpointManager", "ls_dir", "verify_dir", "prune_dir",
           "managers_created", "known_dirs", "write_arrays",
           "read_arrays", "align_params", "timed_recover",
           "record_recovery"]

FORMAT = 1
_STEP_RE = re.compile(r"^step-(\d{8})$")
_TMP_RE = re.compile(r"^\.tmp-step-(\d{8})-")
_OLD_RE = re.compile(r"^step-(\d{8})\.old$")
# serializes the force-overwrite swap's unavoidable final-dir-absent
# window against concurrent in-process heals (writer thread vs. a
# steps()/verify() call on the step thread)
_SWAP_LOCK = threading.Lock()

# in-process registry read by mxlint's elastic runtime pass (MXL501
# runtime form: "N steps ran and nobody constructed a manager"; MXL502:
# integrity of every directory this process checkpointed into)
_reg_lock = threading.Lock()
_managers_created = 0
_known_dirs: set = set()


def managers_created() -> int:
    with _reg_lock:
        return _managers_created


def known_dirs() -> List[str]:
    with _reg_lock:
        return sorted(_known_dirs)


def _note_manager(directory: str):
    global _managers_created
    with _reg_lock:
        _managers_created += 1
        _known_dirs.add(directory)


def _reset_registry():
    """Test hook."""
    global _managers_created
    with _reg_lock:
        _managers_created = 0
        _known_dirs.clear()


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step-{step:08d}")


def _committed_steps(directory: str) -> List[int]:
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if m and os.path.exists(os.path.join(directory, name,
                                             "manifest.json")):
            out.append(int(m.group(1)))
    return sorted(out)


def _partial_dirs(directory: str) -> List[str]:
    if not os.path.isdir(directory):
        return []
    return sorted(n for n in os.listdir(directory) if _TMP_RE.match(n))


def _heal_dir(directory: str):
    """Repair a crash inside a ``force=True`` overwrite swap.

    The swap is rename(final -> final.old); rename(tmp -> final);
    rmtree(old).  A crash between the two renames leaves ONLY
    ``step-N.old`` — the previous checkpoint, demoted but intact — so
    it is renamed back and stays authoritative; with both present the
    swap committed and the leftover is dropped.  Every public entry
    point (manager init/save/restore, ls/verify/prune) heals first, so
    the "a crash at ANY point leaves the previous checkpoint
    authoritative" guarantee covers the overwrite path too.

    A LIVE writer mid-swap is distinguished from a crashed one:
    in-process, ``_SWAP_LOCK`` serializes heal against the swap's two
    renames; cross-process (``mxckpt`` against a live volume), the
    heal re-checks after a short grace delay and skips when the final
    dir has (re)appeared — the writer won the race."""
    if not os.path.isdir(directory):
        return
    with _SWAP_LOCK:
        for name in os.listdir(directory):
            if not _OLD_RE.match(name):
                continue
            old = os.path.join(directory, name)
            final = os.path.join(directory, name[:-len(".old")])
            if not os.path.exists(final):
                # possibly a cross-process writer between its two
                # renames rather than a crash: give it a beat
                time.sleep(0.05)
            if os.path.exists(final):
                shutil.rmtree(old, ignore_errors=True)
            else:
                try:
                    os.rename(old, final)
                except OSError:
                    pass


# -- RNG stream capture ------------------------------------------------------

def _rng_export() -> Dict[str, Any]:
    """Serialize the global RNG stream (``random._keys``) so a restore
    continues the exact dropout/sampling sequence an uninterrupted run
    would have produced."""
    from .. import random as _rnd
    import jax
    out = {"seed": int(_rnd._keys.get("__seed__", _rnd._DEFAULT_SEED)),
           "keys": []}
    for ctx, k in _rnd._keys.items():
        if ctx == "__seed__":
            continue
        data = np.asarray(jax.random.key_data(k))
        out["keys"].append({
            "device_type": ctx.device_type,
            "device_id": int(ctx.device_id),
            "dtype": str(data.dtype),
            "data": data.tolist()})
    return out


def _rng_restore(rng: Dict[str, Any]):
    from .. import random as _rnd
    from ..context import Context
    import jax
    import jax.numpy as jnp
    keys: Dict[Any, Any] = {"__seed__": int(rng.get("seed", 0))}
    for rec in rng.get("keys", ()):
        data = jnp.asarray(np.asarray(
            rec["data"], dtype=np.dtype(rec.get("dtype", "uint32"))))
        keys[Context(rec["device_type"], rec["device_id"])] = \
            jax.random.wrap_key_data(data)
    _rnd._keys.clear()
    _rnd._keys.update(keys)


def _device_copy(a):
    """Device-side snapshot copy: decouples the checkpoint from the
    next step's buffer donation (the live buffer may be consumed by
    the time the background writer gathers it).  Async — the step loop
    is not blocked."""
    import jax.numpy as jnp
    try:
        return jnp.copy(a)
    except Exception:
        return a


def _npy_bytes(host: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, host, allow_pickle=False)
    return buf.getvalue()


# -- shared shard IO ---------------------------------------------------------
# ONE writer/reader pair for hashed .npy shard dirs, shared by
# CheckpointManager._write/_load_checkpoint AND write_arrays/
# read_arrays (the store under checkpoint.OrbaxCheckpoint) — the same
# fault hooks, hashing, atomic-manifest, and integrity checks apply to
# both formats because they ARE the same format (different manifest
# kinds).

def _write_shard(tmp: str, shards: List[dict], name: str, arr,
                 kind: str = "array", index=None, leaf=None,
                 spec=None) -> None:
    """Append one hashed ``.npy`` shard under ``tmp/shards`` and its
    manifest record to ``shards`` (fault points ``host_copy`` /
    ``checkpoint_write`` fire here for every writer)."""
    if faults._active:
        faults.maybe_fire("host_copy", name=name)
    host = np.asarray(arr)
    data = _npy_bytes(host)
    fname = f"shards/{len(shards):03d}.npy"
    if faults._active:
        faults.maybe_fire("checkpoint_write", name=name)
    with open(os.path.join(tmp, fname), "wb") as f:
        f.write(data)
    shards.append({
        "file": fname, "kind": kind, "name": name,
        "index": index, "leaf": leaf,
        "shape": [int(d) for d in host.shape],
        "dtype": str(host.dtype),
        "sharding": spec or "()",
        "sha256": hashlib.sha256(data).hexdigest()})


def _write_manifest(tmp: str, manifest: dict) -> None:
    """Write ``tmp/manifest.json`` atomically (part + replace): the
    manifest is the commit marker WITHIN the dir, so it lands last and
    whole."""
    mtmp = os.path.join(tmp, "manifest.json.part")
    with open(mtmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(mtmp, os.path.join(tmp, "manifest.json"))


def _atomic_publish(tmp: str, final: str) -> None:
    """Publish ``tmp`` as ``final``: one rename, or — when ``final``
    exists — the ``.old`` overwrite swap, serialized against
    concurrent in-process heals (the final-absent window between the
    two renames must not race ``_heal_dir``)."""
    if os.path.exists(final):
        old = final + ".old"
        with _SWAP_LOCK:
            shutil.rmtree(old, ignore_errors=True)
            os.rename(final, old)
            os.rename(tmp, final)
            shutil.rmtree(old, ignore_errors=True)
    else:
        os.rename(tmp, final)


def _load_manifest_json(path: str, kind: str,
                        missing_msg: Optional[str] = None) -> dict:
    """Parse + validate ``path/manifest.json`` (kind + format);
    raises ``MXNetError`` for anything short of a committed, well-
    formed manifest of the expected kind."""
    mpath = os.path.join(path, "manifest.json")
    if not os.path.exists(mpath):
        raise MXNetError(missing_msg or (
            f"{path} holds no manifest.json — not a committed "
            "checkpoint (a crashed write leaves only temp dirs)"))
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise MXNetError(
            f"corrupt checkpoint manifest {mpath}: {e!r}") from e
    if manifest.get("kind") != kind or manifest.get("format") != FORMAT:
        raise MXNetError(
            f"{mpath} kind/format mismatch (want {kind!r} v{FORMAT})")
    return manifest


def _commit_shard_dir(tmp: str, final: str, kind: str, write_shards,
                      extra: Optional[dict] = None) -> List[dict]:
    """THE shard-dir commit sequence, shared by
    ``CheckpointManager._write`` and :func:`write_arrays` (one
    definition of the inventory-and-commit protocol): create
    ``tmp/shards``, let ``write_shards(tmp, rows)`` append the hashed
    shard rows, land the manifest (format/kind/created + ``extra`` +
    the rows) LAST within the dir, then publish atomically.  Returns
    the shard rows."""
    os.makedirs(os.path.join(tmp, "shards"))
    rows: List[dict] = []
    write_shards(tmp, rows)
    manifest = {"format": FORMAT, "kind": kind,
                "created": time.time(), **(extra or {}),
                "shards": rows}
    _write_manifest(tmp, manifest)
    _atomic_publish(tmp, final)    # THE commit point
    return rows


def _read_shard_dir(path: str, kind: str, verify: bool = True,
                    missing_msg: Optional[str] = None):
    """The read half of the shard-dir protocol (shared by
    ``_load_checkpoint`` and :func:`read_arrays`):
    ``(manifest, [(record, host array)])`` with every integrity
    failure raised as ``MXNetError``."""
    manifest = _load_manifest_json(path, kind, missing_msg=missing_msg)
    return manifest, _read_shard_payloads(path, manifest, verify)


def _read_shard_payloads(path: str, manifest: dict,
                         verify: bool) -> List[tuple]:
    """``[(record, host_array)]`` for every manifest shard, with
    integrity failures (unreadable / hash mismatch / invalid payload /
    shape drift) raised as ``MXNetError`` instead of returning
    garbage."""
    out = []
    for rec in manifest.get("shards", ()):
        spath = os.path.join(path, rec["file"])
        try:
            with open(spath, "rb") as f:
                data = f.read()
        except OSError as e:
            raise MXNetError(
                f"checkpoint shard {spath} unreadable: {e!r}") from e
        if verify and hashlib.sha256(data).hexdigest() != \
                rec.get("sha256"):
            raise MXNetError(
                f"checkpoint shard {rec['file']} ({rec['name']}) "
                f"failed its sha256 check in {path} — the checkpoint "
                "is corrupt; restore an earlier step")
        try:
            host = np.load(io.BytesIO(data), allow_pickle=False)
        except Exception as e:
            raise MXNetError(
                f"checkpoint shard {rec['file']} is not a valid .npy "
                f"payload: {e!r}") from e
        if list(host.shape) != list(rec.get("shape", host.shape)):
            raise MXNetError(
                f"checkpoint shard {rec['file']} shape {host.shape} "
                f"!= manifest {rec.get('shape')}")
        out.append((rec, host))
    return out


def _snapshot_payload(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Decouple every device array in a trainer's elastic payload from
    the next step's donation: async device-side copies taken on the
    caller thread, in ONE walk over the three array families (params,
    optimizer-state leaves, residuals).  This is the single place the
    payload's array inventory is enumerated for copying — ZeRO's
    sharded state rows ride the same ``states`` family, so the sharded
    save path copies each buffer exactly once (the PR 7 follow-up:
    ``save()`` used to repeat this walk inline per family, and the
    sharded path would have added a third copy of it)."""
    payload["params"] = [(n, _device_copy(a), s)
                         for n, a, s in payload["params"]]
    payload["states"] = [(i, j, _device_copy(a))
                         for i, j, a in payload["states"]]
    if payload.get("residuals"):
        payload["residuals"] = [_device_copy(a)
                                for a in payload["residuals"]]
    return payload


def _payload_shards(tmp: str, payload: Dict[str, Any]) -> \
        List[Dict[str, Any]]:
    """Write every payload array as a checkpoint shard and return the
    manifest rows — the single definition of the payload -> shard
    naming/layout (``_write`` and any future exporter share it; the
    inverse lives in ``restore()``'s shard -> payload rebuild)."""
    shards: List[Dict[str, Any]] = []
    for i, (name, arr, spec) in enumerate(payload["params"]):
        _write_shard(tmp, shards, name, arr, kind="param",
                     index=i, spec=spec)
    for i, j, arr in payload["states"]:
        _write_shard(tmp, shards, f"state:{i}:{j}", arr,
                     kind="state", index=i, leaf=j)
    for j, arr in enumerate(payload.get("residuals") or ()):
        _write_shard(tmp, shards, f"residual:{j}", arr,
                     kind="residual", leaf=j)
    return shards


class CheckpointManager:
    """Durable train-state checkpoints for one trainer.

    Args:
      directory: checkpoint root (created on first save).
      trainer: a ``parallel.DataParallelTrainer``, a
        ``gluon.CompiledStep``, or a ``gluon.Trainer`` — anything
        implementing the ``_elastic_export``/``_elastic_restore``
        protocol.  May be passed later via ``restore(into=...)``.
      keep: committed checkpoints retained (default
        ``MXTPU_CHECKPOINT_KEEP``).
      async_save: write in a background thread (default); ``False``
        commits inline before ``save()`` returns.
    """

    def __init__(self, directory: str, trainer=None,
                 keep: Optional[int] = None, async_save: bool = True):
        from .. import envs
        self.directory = os.path.abspath(directory)
        self.trainer = trainer
        self.keep = int(keep) if keep is not None else \
            int(envs.get("MXTPU_CHECKPOINT_KEEP"))
        if self.keep < 1:
            raise MXNetError(f"keep must be >= 1, got {self.keep}")
        self.async_save = bool(async_save)
        self.last_error: Optional[str] = None
        self._pool = None
        self._pending = None
        self._lock = threading.Lock()
        #: the exact-resume data cursor (docs/elasticity.md): the
        #: training loop's loader position (epoch/batch/whatever the
        #: loop needs), stamped into every manifest by save() and
        #: re-installed by restore() — with the RNG stream that
        #: already round-trips, a recover() replays the EXACT batch
        #: stream instead of restarting the loader arbitrarily
        self.cursor: Optional[Dict[str, Any]] = None
        self._scrub_thread = None
        self._scrub_stop = threading.Event()
        #: step last restored through THIS manager — committed dirs
        #: NEWER than it belong to the abandoned pre-rollback timeline,
        #: and a periodic save colliding with one auto-overwrites
        #: instead of failing (see _write)
        self._resume_step: Optional[int] = None
        _heal_dir(self.directory)
        _note_manager(self.directory)

    # -- save ------------------------------------------------------------
    def save(self, step: Optional[int] = None, block: bool = False,
             force: bool = False) -> int:
        """Snapshot the trainer and commit checkpoint ``step``.

        Returns the step number immediately; the gather+write runs on
        the background writer unless ``block=True`` (or the manager was
        built with ``async_save=False``).  A previous in-flight write
        is drained first (double buffering); if it FAILED, the failure
        is recorded (``last_error``, telemetry ``checkpoint_error``)
        and this save proceeds — a dead write must not stop the next
        one.  ``force=True`` overwrites an existing committed step.
        """
        if self.trainer is None:
            raise MXNetError("CheckpointManager has no trainer; pass "
                             "one at construction")
        payload = self.trainer._elastic_export()
        if step is not None:
            payload["step"] = int(step)
        payload["rng"] = _rng_export()
        payload["cursor"] = dict(self.cursor) \
            if self.cursor is not None else None
        # decouple from the next step's donation NOW, on the caller
        # thread (async device-side copies; the writer gathers later)
        _snapshot_payload(payload)
        self._drain(swallow=True)
        if block or not self.async_save:
            self._write(payload, force)
        else:
            with self._lock:
                if self._pool is None:
                    from concurrent.futures import ThreadPoolExecutor
                    self._pool = ThreadPoolExecutor(
                        max_workers=1,
                        thread_name_prefix="mxtpu-ckpt")
                self._pending = self._pool.submit(
                    self._write, payload, force)
        return int(payload["step"])

    def _drain(self, swallow: bool):
        fut = self._pending
        if fut is None:
            return
        self._pending = None
        try:
            fut.result()
        except Exception as e:
            self.last_error = repr(e)
            from .. import telemetry
            telemetry.record_event("checkpoint_error",
                                   error=repr(e)[:300])
            if not swallow:
                raise MXNetError(
                    f"async checkpoint write failed: {e!r}") from e

    def wait(self):
        """Block until the in-flight write commits; raises
        ``MXNetError`` if it failed."""
        self._drain(swallow=False)

    def close(self):
        self.stop_scrub()
        self._drain(swallow=True)
        with self._lock:
            if self._pool is not None:
                self._pool.shutdown(wait=True)
                self._pool = None

    # -- exact-resume data cursor ----------------------------------------
    def set_cursor(self, epoch: int, batch: int, **extra) -> None:
        """Record where the training loop's batch stream stands —
        called once per batch (or per epoch) by the loop.  The NEXT
        ``save()`` stamps it into the manifest; ``restore()``
        re-installs it as ``self.cursor`` so a resumed loop can seek
        its loader to the exact position (the RNG stream already
        round-trips, so data order + augmentation replay exactly —
        docs/elasticity.md, "Exact resume")."""
        cur = {"epoch": int(epoch), "batch": int(batch)}
        cur.update(extra)
        self.cursor = cur

    # -- scrubbing (docs/elasticity.md, "Integrity sentry") --------------
    def scrub(self, quarantine: bool = True) -> dict:
        """Re-verify every committed checkpoint's shard sha256s — the
        at-rest leg of the silent-corruption sentry: a shard that rots
        on disk AFTER its commit passed would otherwise sit in the
        retention window until a recovery needed it, then fail at the
        worst possible moment (or, with verification skipped, restore
        garbage).

        A corrupt checkpoint is QUARANTINED (its dir renamed to
        ``quarantined-step-N``, out of the committed namespace) so
        ``restore()``/``latest_step()`` can never serve it and an
        older clean step becomes the recovery anchor; pass
        ``quarantine=False`` to report only — mxlint MXL505 then
        flags the corrupt dir still standing as a restore target.
        Emits the retained ``scrub_corrupt`` event per bad checkpoint
        and the ``mxtpu_scrub_*`` counters; every verdict lands in
        ``elastic.integrity.scrub_log()`` (the MXL505 input).
        Returns ``{"checked", "corrupt", "quarantined", "rows"}``."""
        from .. import telemetry
        from . import integrity as _integrity
        t0 = time.perf_counter()
        rows = []
        corrupt = 0
        quarantined = []
        for row in verify_dir(self.directory):
            if row.get("partial"):
                continue          # torn temp dirs are MXL502's beat
            rec = {"dir": self.directory, "step": row["step"],
                   "ok": row["ok"], "quarantined": False}
            if not row["ok"]:
                # double-check under the swap lock before believing
                # it: the first pass reads UNSYNCHRONIZED, so a
                # force-overwrite mid-swap (rename final -> .old;
                # rename tmp -> final) can transiently read as
                # corrupt — the background scrubber must never
                # quarantine a healthy, freshly committed step.  The
                # rename also happens under the lock, so it cannot
                # race the writer's own renames.
                src = _step_dir(self.directory, int(row["step"]))
                with _SWAP_LOCK:
                    try:
                        _load_checkpoint(src, verify=True)
                        rec["ok"] = True       # transient: swap race
                    except MXNetError:
                        if quarantine:
                            dst = os.path.join(
                                self.directory,
                                "quarantined-step-"
                                f"{int(row['step']):08d}")
                            try:
                                shutil.rmtree(dst,
                                              ignore_errors=True)
                                os.rename(src, dst)
                                rec["quarantined"] = True
                                quarantined.append(int(row["step"]))
                            except OSError as e:
                                rec["quarantine_error"] = \
                                    repr(e)[:200]
            if not rec["ok"]:
                corrupt += 1
                telemetry.counter(
                    "mxtpu_scrub_corrupt_total",
                    "committed checkpoints the scrubber found "
                    "corrupt at rest").inc()
                telemetry.record_event(
                    "scrub_corrupt", dir=self.directory,
                    step=int(row["step"]),
                    errors=[e[:200] for e in row.get("errors", ())],
                    quarantined=rec["quarantined"])
            _integrity.note_scrub(rec)
            rows.append(rec)
        telemetry.counter(
            "mxtpu_scrub_passes_total",
            "checkpoint scrub passes completed").inc()
        telemetry.counter(
            "mxtpu_scrub_checkpoints_total",
            "committed checkpoints re-verified by the scrubber"
            ).inc(len(rows))
        telemetry.histogram(
            "mxtpu_scrub_seconds",
            "wall clock of one checkpoint scrub pass (s)").observe(
            time.perf_counter() - t0)
        return {"checked": len(rows), "corrupt": corrupt,
                "quarantined": quarantined, "rows": rows}

    def start_scrub(self, every_s: Optional[float] = None) -> bool:
        """Run :meth:`scrub` on a background daemon thread every
        ``every_s`` seconds (default ``MXTPU_SCRUB_EVERY_S``; <= 0
        starts nothing).  Idempotent; :meth:`stop_scrub`/:meth:`close`
        stops it."""
        from .. import envs
        if every_s is None:
            every_s = float(envs.get("MXTPU_SCRUB_EVERY_S"))
        if every_s <= 0 or self._scrub_thread is not None:
            return False
        self._scrub_stop.clear()

        def _loop():
            while not self._scrub_stop.wait(every_s):
                try:
                    self.scrub()
                except Exception as e:
                    from .. import telemetry
                    telemetry.record_event(
                        "checkpoint_error",
                        error=f"scrub failed: {e!r}"[:300])

        self._scrub_thread = threading.Thread(
            target=_loop, name="mxtpu-scrub", daemon=True)
        self._scrub_thread.start()
        return True

    def stop_scrub(self) -> None:
        t = self._scrub_thread
        if t is None:
            return
        self._scrub_stop.set()
        t.join(timeout=5.0)
        self._scrub_thread = None

    def _write(self, payload: Dict[str, Any], force: bool):
        from .. import telemetry
        t0 = time.perf_counter()
        step = int(payload["step"])
        _heal_dir(self.directory)
        final = _step_dir(self.directory, step)
        if os.path.exists(final) and not force:
            resume = self._resume_step
            if resume is not None and step > resume:
                # the committed dir is from the abandoned timeline of a
                # pre-rollback run (we restored an EARLIER step through
                # this manager): the new timeline supersedes it, so the
                # periodic save overwrites instead of silently dying on
                # the writer thread
                force = True
            else:
                raise MXNetError(
                    f"checkpoint step {step} already committed at "
                    f"{final} (pass force=True to overwrite)")
        tmp = os.path.join(self.directory,
                           f".tmp-step-{step:08d}-{os.getpid()}")
        if os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        shards = _commit_shard_dir(
            tmp, final, "mxtpu_elastic_checkpoint",
            lambda t, rows: rows.extend(_payload_shards(t, payload)),
            extra={
                "step": step,
                "trainer": payload.get("kind"),
                "optimizer": payload.get("optimizer"),
                "update_counts": {
                    str(k): int(v) for k, v in
                    (payload.get("update_counts") or {}).items()},
                "num_update": int(payload.get("num_update", step)),
                "mesh": payload.get("mesh"),
                "dp_axis": payload.get("dp_axis"),
                "persist_name": payload.get("persist_name"),
                # the ZeRO layout pin (docs/zero.md): restore converts
                # the sharded state rows to the target trainer's layout
                "zero": payload.get("zero"),
                # the sharding-plan pin (docs/parallelism.md): the
                # canonical plan this checkpoint was saved under — the
                # audit trail a cross-plan restore's reshard report
                # reads
                "plan": payload.get("plan"),
                # the exact-resume data cursor (set_cursor): where the
                # batch stream stood at this commit
                "cursor": payload.get("cursor"),
                "rng": payload["rng"],
            })
        self.prune()
        dt = time.perf_counter() - t0
        telemetry.counter("mxtpu_checkpoints_saved_total",
                          "committed checkpoints").inc()
        telemetry.histogram("mxtpu_checkpoint_save_seconds",
                            "snapshot->commit wall clock (s)"
                            ).observe(dt)
        telemetry.record_event("checkpoint_commit", step=step,
                               seconds=round(dt, 4),
                               shards=len(shards),
                               dir=self.directory)

    # -- inspect ---------------------------------------------------------
    def steps(self) -> List[int]:
        return _committed_steps(self.directory)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def verify(self, step: Optional[int] = None) -> List[dict]:
        return verify_dir(self.directory, step=step)

    def prune(self, keep: Optional[int] = None) -> int:
        return prune_dir(self.directory,
                         keep if keep is not None else self.keep)

    # -- restore ---------------------------------------------------------
    def restore(self, step: Optional[int] = None, into=None,
                restore_rng: bool = True, verify: bool = True,
                invalidate_newer: bool = False) -> int:
        """Load checkpoint ``step`` (default: latest committed) into
        the trainer.  Shard hashes are verified (``verify=False`` skips
        — e.g. for a just-written checkpoint on a slow filesystem);
        any missing/partial/corrupt state raises ``MXNetError``.  When
        the trainer's mesh differs from the saved one, params and
        optimizer state are re-placed through the reshard path
        (fp32-exact).  Returns the restored step.

        Restoring an EARLIER step forks the timeline: checkpoints
        newer than it describe the abandoned run.  With
        ``invalidate_newer=True`` (what ``recover()`` passes) they are
        deleted, so a later crash can never resume from the abandoned
        timeline; the default keeps them on disk for inspection, but
        subsequent saves through this manager overwrite them as the
        new timeline's step counter catches up."""
        from .. import telemetry
        trainer = into if into is not None else self.trainer
        if trainer is None:
            raise MXNetError("restore: no trainer (pass into=...)")
        t0 = time.perf_counter()
        # an in-flight async save must commit (or fail) BEFORE the
        # restore target is chosen and before invalidate_newer runs:
        # a write landing afterwards would resurrect the abandoned
        # timeline as the newest checkpoint (a failed write keeps the
        # previous checkpoint authoritative, so it is swallowed here
        # exactly like close())
        self._drain(swallow=True)
        _heal_dir(self.directory)
        if step is None:
            step = self.latest_step()
            if step is None:
                raise MXNetError(
                    f"no committed checkpoint under {self.directory}")
        path = _step_dir(self.directory, int(step))
        manifest, arrays = _load_checkpoint(path, verify=verify)
        payload = {
            "step": int(manifest["step"]),
            "optimizer": manifest.get("optimizer"),
            "update_counts": {int(k): int(v) for k, v in
                              manifest.get("update_counts", {}).items()},
            "num_update": int(manifest.get("num_update", step)),
            "mesh": manifest.get("mesh"),
            "dp_axis": manifest.get("dp_axis"),
            "persist_name": manifest.get("persist_name"),
            "zero": manifest.get("zero"),
            "plan": manifest.get("plan"),
            "params": [], "states": [], "residuals": [],
        }
        for rec, host in zip(manifest["shards"], arrays):
            if rec["kind"] == "param":
                payload["params"].append(
                    (rec["name"], host, rec.get("sharding")))
            elif rec["kind"] == "state":
                payload["states"].append(
                    (int(rec["index"]), int(rec["leaf"]), host))
            elif rec["kind"] == "residual":
                payload["residuals"].append(host)
        trainer._elastic_restore(payload)
        if restore_rng:
            _rng_restore(manifest.get("rng", {}))
        restored = int(manifest["step"])
        self._resume_step = restored
        # re-install the data cursor this checkpoint was saved under
        # (None for pre-cursor manifests): the resumed loop reads
        # manager.cursor and seeks its loader there — with the RNG
        # restore below, the batch stream replays exactly
        self.cursor = manifest.get("cursor")
        if invalidate_newer:
            dropped = [s for s in self.steps() if s > restored]
            for s in dropped:
                shutil.rmtree(_step_dir(self.directory, s),
                              ignore_errors=True)
            if dropped:
                telemetry.record_event(
                    "checkpoint_invalidate", restored=restored,
                    dropped=dropped, dir=self.directory)
        dt = time.perf_counter() - t0
        telemetry.histogram("mxtpu_checkpoint_restore_seconds",
                            "checkpoint load->applied wall clock (s)"
                            ).observe(dt)
        telemetry.record_event("checkpoint_restore",
                               step=int(manifest["step"]),
                               seconds=round(dt, 4),
                               dir=self.directory)
        return int(manifest["step"])


def record_recovery(where: str, seconds: float, poisoned: bool,
                    **fields) -> None:
    """Emit the recovery telemetry triple — counter, time-to-recover
    histogram, retained ``recovery`` event — in ONE place for every
    recoverable owner (the two train stacks via :func:`timed_recover`,
    the serving plane via ``Server.recover``)."""
    from .. import telemetry
    telemetry.counter("mxtpu_recoveries_total",
                      "recoveries of a poisoned or healthy owner "
                      "(train stacks: checkpoint restore; serving: "
                      "pool rebuild + request replay)").inc()
    telemetry.histogram(
        "mxtpu_recovery_seconds",
        "time to rebuild an owner's dispatchable state after "
        "recover() (s)").observe(seconds)
    telemetry.record_event("recovery", where=where,
                           seconds=round(seconds, 4),
                           poisoned=poisoned, **fields)


def timed_recover(manager: "CheckpointManager", owner, where: str,
                  step: Optional[int] = None,
                  name: Optional[str] = None,
                  was_poisoned: bool = False) -> int:
    """The shared ``recover()`` body (docs/elasticity.md): restore the
    last committed checkpoint (or ``step``) into ``owner`` with the
    timeline FORKED (newer checkpoints invalidated, so a later crash
    can never resume the abandoned run) and emit the recovery
    telemetry triple — counter, latency histogram, retained event.
    ``gluon.CompiledStep.recover`` and ``DataParallelTrainer.recover``
    both delegate here."""
    t0 = time.perf_counter()
    restored = manager.restore(step=step, into=owner,
                               invalidate_newer=True)
    fields = {"step": restored}
    if name is not None:
        fields["name"] = name
    record_recovery(where, time.perf_counter() - t0, was_poisoned,
                    **fields)
    return restored


def write_arrays(path: str, arrays: Dict[str, np.ndarray],
                 kind: str = "mxtpu_array_dict",
                 extra: Optional[dict] = None) -> str:
    """Atomically write a named-array dict as a hashed shard dir (the
    store under ``checkpoint.OrbaxCheckpoint``): everything lands in a
    temp dir, the manifest (with per-shard sha256) is written last,
    and ONE rename publishes ``path``.  An existing ``path`` is
    swapped out, never partially overwritten."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + f".tmp{os.getpid()}"
    # stale temp dirs are crash artifacts the commit protocol already
    # kept invisible — sweep our own pid's leftover plus any OLD
    # foreign one (an hour-stale dir is a crash, a fresh one may be a
    # live writer in another process mid-commit)
    for stale in _glob.glob(path + ".tmp*"):
        if stale != tmp:
            try:
                if time.time() - os.path.getmtime(stale) < 3600:
                    continue
            except OSError:
                continue
        shutil.rmtree(stale, ignore_errors=True)

    def _fill(t, rows):
        for name, value in arrays.items():
            _write_shard(t, rows, name, value)

    _commit_shard_dir(tmp, path, kind, _fill, extra=extra)
    return path


def read_arrays(path: str, kind: str = "mxtpu_array_dict",
                verify: bool = True):
    """Load a :func:`write_arrays` dir: ``(manifest, {name: host})``.
    Raises ``MXNetError`` for partial/corrupt/foreign content instead
    of returning garbage."""
    path = os.path.abspath(path)
    old = path + ".old"
    if os.path.isdir(old):
        # crash inside write_arrays' overwrite swap: with the final
        # path present the swap committed (drop the leftover); without
        # it the previous content is the survivor — restore it
        if os.path.isdir(path):
            shutil.rmtree(old, ignore_errors=True)
        else:
            try:
                os.rename(old, path)
            except OSError:
                pass
    if not os.path.isdir(path):
        raise MXNetError(f"no checkpoint at {path}")
    manifest, payloads = _read_shard_dir(
        path, kind, verify,
        missing_msg=f"{path} holds no manifest.json — not a committed "
                    "checkpoint (or a pre-elastic artifact)")
    return manifest, {rec["name"]: host for rec, host in payloads}


def align_params(param_names: List[str], payload_params) -> List[tuple]:
    """``[(host, spec)]`` aligned with ``param_names``.

    Exact name match when the name sets agree; otherwise positional —
    gluon auto-naming drifts with construction ORDER inside one
    process (``hybridsequential0_`` -> ``hybridsequential1_``), while
    the save order (``collect_params`` order) is stable for the same
    model code.  A count mismatch is a different model and raises;
    per-param shape checks downstream catch subtler misalignment."""
    by_name = {n: (h, s) for n, h, s in payload_params}
    if set(param_names) <= set(by_name):
        return [by_name[n] for n in param_names]
    if len(param_names) != len(payload_params):
        missing = sorted(set(param_names) - set(by_name))[:4]
        raise MXNetError(
            f"checkpoint holds {len(payload_params)} params but the "
            f"trainer has {len(param_names)} (first missing names: "
            f"{missing}) — it describes a different model")
    return [(h, s) for _n, h, s in payload_params]


def _load_checkpoint(path: str, verify: bool = True):
    """(manifest, [host arrays aligned with manifest["shards"]]).
    Raises ``MXNetError`` for anything short of a complete, committed,
    hash-clean checkpoint."""
    manifest, payloads = _read_shard_dir(
        path, "mxtpu_elastic_checkpoint", verify,
        missing_msg=f"{path} is not a committed checkpoint (no "
                    "manifest.json — a crashed write leaves only "
                    ".tmp-step-* dirs)")
    return manifest, [host for _rec, host in payloads]


# -- directory-level tooling (tools/mxckpt.py, mxlint MXL502) ---------------

def ls_dir(directory: str) -> List[dict]:
    """One row per committed checkpoint + one per torn temp dir."""
    directory = os.path.abspath(directory)
    _heal_dir(directory)
    rows = []
    for step in _committed_steps(directory):
        path = _step_dir(directory, step)
        row = {"step": step, "path": path, "partial": False}
        try:
            with open(os.path.join(path, "manifest.json")) as f:
                m = json.load(f)
            shards = m.get("shards", [])
            row.update(ok=True, shards=len(shards),
                       trainer=m.get("trainer"),
                       optimizer=m.get("optimizer"),
                       mesh=m.get("mesh"),
                       created=m.get("created"),
                       bytes=sum(os.path.getsize(os.path.join(
                           path, s["file"]))
                           for s in shards
                           if os.path.exists(
                               os.path.join(path, s["file"]))))
        except Exception as e:
            row.update(ok=False, error=repr(e)[:200])
        rows.append(row)
    for name in _partial_dirs(directory):
        rows.append({"step": None, "path": os.path.join(directory, name),
                     "partial": True, "ok": False,
                     "error": "uncommitted write (crash or in flight)"})
    return rows


def verify_dir(directory: str, step: Optional[int] = None) -> List[dict]:
    """Full integrity pass: manifest parse + per-shard sha256.  One row
    per checkpoint with ``ok`` and the failing shards listed."""
    directory = os.path.abspath(directory)
    _heal_dir(directory)
    steps = [step] if step is not None else _committed_steps(directory)
    rows = []
    for s in steps:
        path = _step_dir(directory, int(s))
        row = {"step": int(s), "path": path, "ok": True, "errors": []}
        try:
            _load_checkpoint(path, verify=True)
        except MXNetError as e:
            row["ok"] = False
            row["errors"].append(str(e))
        rows.append(row)
    for name in _partial_dirs(directory):
        rows.append({"step": None,
                     "path": os.path.join(directory, name),
                     "ok": False, "partial": True,
                     "errors": ["uncommitted partial write"]})
    return rows


def prune_dir(directory: str, keep: int) -> int:
    """Remove committed checkpoints beyond the ``keep`` most recently
    COMMITTED (manifest ``created``, not step number: after a rollback
    the new timeline's low-numbered saves are newer commits than the
    abandoned high-numbered ones and must survive them — the abandoned
    steps age out instead) and every torn temp dir; returns the number
    of dirs removed."""
    directory = os.path.abspath(directory)
    _heal_dir(directory)
    removed = 0

    def _created(s: int) -> float:
        p = _step_dir(directory, s)
        try:
            with open(os.path.join(p, "manifest.json")) as f:
                return float(json.load(f).get("created", 0.0))
        except Exception:
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0

    steps = sorted(_committed_steps(directory),
                   key=lambda s: (_created(s), s))
    for s in steps[:-keep] if keep > 0 else steps:
        shutil.rmtree(_step_dir(directory, s), ignore_errors=True)
        removed += 1
    for name in _partial_dirs(directory):
        shutil.rmtree(os.path.join(directory, name),
                      ignore_errors=True)
        removed += 1
    return removed
