"""Mesh-change array redistribution (checkpoint restore + live moves).

The paper trail is "Memory-efficient array redistribution through
portable collective communication" (arXiv:2112.01075, PAPERS.md): when
a job restarts on a different chip count or mesh shape, the saved
layout and the target layout differ and every array must move —
without a gather-to-host round trip when both layouts are live on
device.

Two cases land here:

* **live → live** (``redistribute``): source and target sharding are
  both device-resident.  When the two meshes cover the same device
  set, the move is ONE compiled identity program with pinned
  ``out_shardings`` — XLA lowers the layout change to the minimal
  all-gather / dynamic-slice / collective-permute program (the
  portable-collective formulation of 2112.01075 is what the SPMD
  partitioner implements).  Across different device sets,
  ``jax.device_put`` performs the transfer through the runtime's
  resharding machinery.
* **host → live** (checkpoint restore, ``place``): the shard files
  hold the full logical array; placement is a sharded ``device_put``
  onto the target spec — each device receives only its slice.

``plan`` renders the per-array move as a human-readable op list
(``all_gather(dp:8)``, ``slice(dp:4)``, ...) for telemetry and the
restore report; it is derived purely from (shape, src spec/mesh, dst
spec/mesh), never from device state.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["spec_from_str", "spec_to_str", "plan", "place",
           "redistribute", "plan_moves", "redistribute_plan"]


def spec_to_str(spec) -> str:
    """Canonical string form of a PartitionSpec (manifest field)."""
    return str(tuple(spec)) if spec is not None else "()"


def spec_from_str(text: Optional[str]):
    """Parse the manifest's sharding-spec string back into a
    ``PartitionSpec``.  Accepts the ``str(spec)`` /
    ``str(tuple(spec))`` forms the trainers record; unknown/empty
    forms mean "replicated"."""
    from jax.sharding import PartitionSpec as P
    if not text:
        return P()
    t = text.strip()
    if t.startswith("PartitionSpec"):
        t = t[len("PartitionSpec"):]
    t = t.strip()
    if t in ("", "()", "(,)"):
        return P()
    if not (t.startswith("(") and t.endswith(")")):
        raise MXNetError(f"unparseable sharding spec {text!r}")
    # the recorded form is str(tuple(spec)) — a python literal whose
    # entries are axis names, None, or TUPLES of axis names (a dim
    # sharded over several mesh axes), so a flat comma split cannot
    # parse it
    import ast
    try:
        val = ast.literal_eval(t)
    except (ValueError, SyntaxError):
        raise MXNetError(f"unparseable sharding spec {text!r}")
    if not isinstance(val, tuple):
        raise MXNetError(f"unparseable sharding spec {text!r}")
    for e in val:
        if not (e is None or isinstance(e, str) or
                (isinstance(e, tuple) and
                 all(isinstance(n, str) for n in e))):
            raise MXNetError(f"unparseable sharding spec {text!r}")
    return P(*val)


def _axis_factor(spec, mesh_axes: Dict[str, int]) -> Dict[int, Tuple]:
    """dim index -> (axis name, shard count) for the sharded dims."""
    out = {}
    for d, entry in enumerate(tuple(spec or ())):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for name in names:
            n *= int(mesh_axes.get(name, 1))
        out[d] = (names, n)
    return out


def plan(shape: Sequence[int], src_spec, src_mesh: Dict[str, int],
         dst_spec, dst_mesh: Dict[str, int]) -> List[str]:
    """The collective moves a (src layout) -> (dst layout) transition
    needs, as op strings.  Replicated->replicated across a size change
    is a pure broadcast/subset (``replicate``); a shrinking sharded dim
    all-gathers then re-slices; identical layouts are a no-op."""
    src = _axis_factor(src_spec, src_mesh)
    dst = _axis_factor(dst_spec, dst_mesh)
    steps: List[str] = []
    for d in sorted(set(src) | set(dst)):
        s = src.get(d)
        t = dst.get(d)
        if s == t and (s is None or
                       src_mesh.get(s[0][0]) == dst_mesh.get(s[0][0])):
            continue
        if s is not None:
            names, n = s
            steps.append(f"all_gather(dim={d}, "
                         f"{'x'.join(names)}:{n})")
        if t is not None:
            names, n = t
            steps.append(f"slice(dim={d}, {'x'.join(names)}:{n})")
    if not steps and dict(src_mesh) != dict(dst_mesh):
        steps.append(
            f"replicate({'x'.join(f'{k}:{v}' for k, v in dst_mesh.items())})")
    return steps


def place(host_array, mesh, spec):
    """Host array -> device array sharded per ``spec`` on ``mesh``
    (the checkpoint-restore leg: each device materializes its slice)."""
    import jax
    from jax.sharding import NamedSharding
    return jax.device_put(host_array, NamedSharding(mesh, spec))


def redistribute(arrays, target_shardings):
    """Move live device arrays onto ``target_shardings`` (one per
    array), on-device when possible.

    Same device set on both sides: ONE jitted identity with pinned
    ``out_shardings`` — the compiled all-gather/slice/permute program.
    Different device sets (a 4-chip restart inheriting 8-chip arrays):
    ``jax.device_put`` per array via the runtime's transfer engine.
    fp32-exact either way (layout moves never touch element values).
    """
    import jax
    arrays = list(arrays)
    targets = list(target_shardings)
    if not arrays:
        return []
    try:
        src_devs = {d for a in arrays for d in a.sharding.device_set}
        dst_devs = {d for s in targets for d in s.device_set}
    except AttributeError:
        src_devs, dst_devs = None, ()
    if src_devs is not None and src_devs == dst_devs:
        try:
            # every caller rebinds its holders to the moved arrays, so
            # the sources are dead on return: donate them, or the one-
            # program layout move transiently holds model+state twice
            moved = jax.jit(lambda *xs: xs,
                            out_shardings=tuple(targets),
                            donate_argnums=tuple(range(len(arrays)))
                            )(*arrays)
            return list(moved)
        except Exception:
            # compile-stage failures leave every input alive and the
            # per-array fallback below absorbs them; an EXECUTION
            # failure may have consumed the donated sources — the
            # fallback would then raise an unrelated deleted-array
            # error, so surface the true cause instead
            def _dead(a):
                try:
                    return a.is_deleted()
                except Exception:
                    return False
            if any(_dead(a) for a in arrays):
                raise
    return [jax.device_put(a, s) for a, s in zip(arrays, targets)]


# -- plan-to-plan redistribution (docs/parallelism.md, reshard matrix) ------

def plan_moves(named_shapes, plan_src, plan_dst,
               dtype_bytes: int = 4) -> Dict[str, dict]:
    """The per-param move report of a ``plan_src -> plan_dst``
    redistribution: ``{name: {"moves": [...], "nbytes": int}}`` for
    every param whose layout actually changes (``moves`` from
    :func:`plan`; ``nbytes`` is the GLOBAL tensor size — the upper
    bound on bytes the move touches).  Derived purely from shapes +
    the two plans, never from device state — the ``mxplan diff`` /
    bench accounting input."""
    out: Dict[str, dict] = {}
    for row in plan_src.diff(plan_dst, named_shapes,
                             dtype_bytes=dtype_bytes):
        out[row["name"]] = {"moves": row["moves"],
                            "nbytes": row["nbytes"],
                            "from_spec": row["from_spec"],
                            "to_spec": row["to_spec"]}
    return out


def redistribute_plan(named_arrays, plan_dst, mesh=None):
    """Move arrays saved/live under ANY source plan onto ``plan_dst``'s
    resolution — between any two plans, not just dp-size changes
    (fp32-exact: layout moves never touch element values).

    ``named_arrays``: ``[(param_path, array)]`` — live device arrays
    (the one-donated-program move of :func:`redistribute` when the
    device sets coincide) or host arrays (sharded ``device_put`` per
    :func:`place`).  ``mesh`` defaults to ``plan_dst.build_mesh()``.
    Returns the moved arrays in order.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P
    named_arrays = list(named_arrays)
    if mesh is None:
        mesh = plan_dst.build_mesh()
    targets = []
    for name, a in named_arrays:
        spec, _idx = plan_dst.spec_for(name, a.shape)
        targets.append(NamedSharding(mesh, P(*spec)))
    return redistribute([a for _n, a in named_arrays], targets)
