"""Deterministic fault injection for the elastic training plane.

A recovery path that is never exercised is a recovery path that does
not work.  This module turns every failure mode the elastic subsystem
claims to survive into a knob the tier-1 CPU suite can pull on demand:

``MXTPU_FAULT_INJECT`` holds a ``;``-separated list of fault specs::

    point[:qualifier[,qualifier...]]

    dispatch:step=7            # raise before the step-7 dispatch runs
    dispatch_post:nth=2        # 2nd dispatch: consume the donated
                               # buffers (what TPU donation does), then
                               # raise -> the poison protocol fires
    checkpoint_write:nth=2     # crash while writing the 2nd shard
    host_copy                  # fail the device->host snapshot copy
    dispatch:prob=0.05         # every arrival fires with p=0.05, from
                               # the MXTPU_FAULT_SEED RNG stream —
                               # random plans replay deterministically
    dispatch_hang:ms=500       # HANG the dispatch 500 ms (watchdog-
                               # visible), then consume the donated
                               # buffers and raise

Injection points (the hooks live on the real code paths, not in test
shims):

* ``dispatch`` — engine ``invoke_compiled`` / the SPMD trainer's fused
  dispatch, BEFORE the executable runs: buffers stay alive, so this is
  the transient-failure shape the bounded-retry path must absorb.
* ``dispatch_post`` — same seam, but the donated input buffers are
  deleted first (simulating executable-consumed donation, which the
  CPU backend never does on its own): the caller's consumed-probe sees
  dead buffers and the poison/recover protocol must engage.
* ``checkpoint_write`` — inside the checkpoint writer, between shard
  writes and before the commit rename: the temp dir must be left
  uncommitted and the previous checkpoint must stay authoritative.
* ``host_copy`` — the device->host copy of the checkpoint snapshot.
* ``nonfinite_grad`` — corrupts instead of crashing: the step plants a
  NaN in its input batch, so the compiled program produces a nonfinite
  loss/gradients and the health plane's detection, skip gate, and
  rollback paths are exercised (docs/observability.md).
* ``dispatch_hang`` — the dispatch HANGS (``time.sleep``, default
  ``:ms=1000``) instead of raising — the failure mode a watchdog
  exists for (``elastic.guardian.Guardian``).  When the sleep ends the
  donated buffers are consumed and :class:`FaultError` raises, so an
  un-watched hang still resolves into the familiar poison protocol
  (the drill terminates instead of wedging the suite).
* ``preempt_signal`` — a synthetic preemption: when due, the guardian
  plane's step-owner heartbeat delivers a real ``SIGTERM`` to this
  process (``os.kill``), driving the installed
  :class:`~.guardian.PreemptionGuard`'s drain path end to end.  Only
  consulted while a guardian/preemption guard is installed — without
  one the point never fires (and a raw SIGTERM would simply kill the
  process, which is not a drill).
* ``corrupt_param`` / ``corrupt_grad`` / ``corrupt_wire`` — the
  SILENT-corruption points (docs/elasticity.md, "Integrity sentry"):
  no raise, no NaN — a seeded single-bit flip in one device's live
  param buffer (host-side), or in one device's post-collective
  gradient / received collective payload (the in-graph ctl-driven XOR
  the step stacks bake while one of these is configured).  Qualifiers
  ``device=D,leaf=J,bit=B`` pin the target; unspecified fields draw
  from the ``MXTPU_FAULT_SEED`` stream.  The cross-replica integrity
  fingerprints (``elastic.integrity``) are the detector these drills
  exist to red→green test.
* ``resize_drain`` / ``resize_prewarm`` / ``resize_reshard`` /
  ``resize_swap`` — the four transition points of a LIVE elastic
  resize (``elastic.resize.ResizeController``, docs/elasticity.md
  "Live resize").  A fault at ``resize_drain``/``resize_prewarm``
  aborts with the owner untouched on the OLD mesh; one at
  ``resize_reshard``/``resize_swap`` lands after the drain checkpoint
  committed, so the controller crash-heals onto the NEW mesh from it —
  either way the owner ends on a consistent mesh, never poisoned with
  no recovery path.  The reshard's buffer moves go through
  :func:`on_dispatch` with the PRE-FILTERED donated set (``donate=
  None``), so a ``dispatch_post`` drill during a resize consumes only
  buffers the move was going to donate anyway.

Qualifiers: ``nth=N`` fires on the Nth arrival at the point (1-based,
default 1); ``step=N`` fires on the first arrival at or after global
train step N (``telemetry.current_step()``); ``times=K`` repeats the
fault K times (default 1; 0 = unlimited); ``prob=P`` (float in [0,1])
makes each eligible arrival fire with probability P, drawn from a
``random.Random`` seeded by ``MXTPU_FAULT_SEED`` (or ``configure``'s
``seed=``) — the same seed + the same arrival sequence replays the
same random plan exactly; ``ms=N`` sets the ``dispatch_hang`` sleep in
milliseconds.  Every spec is one-shot by default so a retry/recovery
can succeed deterministically — EXCEPT ``prob=`` specs, which default
to unlimited ``times`` (a probabilistic plan that retired after one
hit would not be a soak).

The module is import-light (no jax) and costs one module-attribute
read (``_active``) per hook when no fault is configured.
"""
from __future__ import annotations

import os
import random as _random
import threading
from typing import Dict, List, Optional

__all__ = ["FaultError", "FaultSpec", "configure", "configure_from_env",
           "clear", "active", "fired", "maybe_fire", "on_dispatch",
           "note_corruption_applied",
           "nonfinite_due", "preempt_due", "corrupt_due",
           "corrupt_armed", "POINTS", "CORRUPT_POINTS",
           "HANG_DEFAULT_MS"]

#: the injection points wired into the runtime (unknown points parse —
#: forward compatibility — but are reported by :func:`configure`)
POINTS = ("dispatch", "dispatch_post", "dispatch_hang",
          "checkpoint_write", "host_copy",
          "nonfinite_grad", "preempt_signal",
          "resize_drain", "resize_prewarm",
          "resize_reshard", "resize_swap",
          "corrupt_param", "corrupt_grad", "corrupt_wire")

#: the silent-corruption points (docs/elasticity.md, "Integrity
#: sentry"): they CORRUPT instead of crashing — ``corrupt_param``
#: flips a bit in one device's live param buffer (host-side, real
#: physical state corruption); ``corrupt_grad``/``corrupt_wire`` drive
#: the ctl-driven in-graph XOR the step stacks bake while one of them
#: is configured (flipping a bit in the targeted device's
#: post-collective gradient / received collective payload).  Payload
#: qualifiers ``device=D``, ``leaf=J``, ``bit=B`` pin the target;
#: unspecified ones draw from the ``MXTPU_FAULT_SEED`` RNG, so a bare
#: ``corrupt_param`` drill is random but replays exactly.
CORRUPT_POINTS = ("corrupt_param", "corrupt_grad", "corrupt_wire")

#: default ``dispatch_hang`` sleep when the spec carries no ``ms=``
HANG_DEFAULT_MS = 1000


class FaultError(RuntimeError):
    """An injected fault (subclasses RuntimeError so the transient-
    failure retry classifier treats it like a real runtime error)."""


class FaultSpec:
    __slots__ = ("point", "nth", "step", "times", "prob", "ms",
                 "device", "leaf", "bit", "fired_count")

    def __init__(self, point: str, nth: Optional[int] = None,
                 step: Optional[int] = None, times: int = 1,
                 prob: Optional[float] = None,
                 ms: Optional[int] = None,
                 device: Optional[int] = None,
                 leaf: Optional[int] = None,
                 bit: Optional[int] = None):
        self.point = point
        self.nth = nth
        self.step = step
        self.times = times
        self.prob = prob
        self.ms = ms
        self.device = device
        self.leaf = leaf
        self.bit = bit
        self.fired_count = 0

    @property
    def exhausted(self) -> bool:
        # times=0 means unlimited (the prob= default): the spec stays
        # armed for the life of the configuration
        return self.times > 0 and self.fired_count >= self.times

    def __repr__(self):
        quals = []
        if self.nth is not None:
            quals.append(f"nth={self.nth}")
        if self.step is not None:
            quals.append(f"step={self.step}")
        if self.prob is not None:
            quals.append(f"prob={self.prob:g}")
        if self.ms is not None:
            quals.append(f"ms={self.ms}")
        for k in ("device", "leaf", "bit"):
            v = getattr(self, k)
            if v is not None:
                quals.append(f"{k}={v}")
        if self.times != (0 if self.prob is not None else 1):
            quals.append(f"times={self.times}")
        return self.point + (":" + ",".join(quals) if quals else "")


_lock = threading.Lock()
_specs: List[FaultSpec] = []
_counts: Dict[str, int] = {}
_fired: List[str] = []
#: fast-path flag: hooks read this one attribute and return when False
_active = False
#: sticky while a configuration holds an IN-GRAPH corruption spec
#: (``corrupt_grad``/``corrupt_wire``): the step stacks bake the
#: ctl-driven XOR block while this is set, and the flag deliberately
#: survives spec exhaustion — it flips only at configure()/clear(), so
#: a fired one-shot drill costs ONE retrace to arm and one to disarm,
#: never a rebuild mid-drill
_corrupt_armed = False
#: the prob= qualifier's RNG — re-seeded by every :func:`configure`
#: (from ``seed=`` or ``MXTPU_FAULT_SEED``), so a random plan replays
#: deterministically: same seed + same arrival sequence = same firings
_rng = _random.Random(0)


def _parse(text: str) -> List[FaultSpec]:
    specs: List[FaultSpec] = []
    for raw in text.replace("\n", ";").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        point, _, qual = raw.partition(":")
        point = point.strip()
        kw: Dict[str, float] = {}
        for q in qual.split(","):
            q = q.strip()
            if not q:
                continue
            k, _, v = q.partition("=")
            k = k.strip()
            if k not in ("nth", "step", "times", "prob", "ms",
                         "device", "leaf", "bit") \
                    or not v.strip():
                raise ValueError(
                    f"bad fault qualifier {q!r} in {raw!r} "
                    "(expected nth=N, step=N, times=K, prob=P, "
                    "ms=N, device=D, leaf=J, or bit=B)")
            try:
                kw[k] = float(v) if k == "prob" else int(v)
            except ValueError:
                raise ValueError(
                    f"bad fault qualifier value {q!r} in {raw!r}")
        prob = kw.get("prob")
        if prob is not None and not 0.0 <= prob <= 1.0:
            raise ValueError(
                f"prob must be in [0, 1], got {prob} in {raw!r}")
        # a probabilistic spec defaults to unlimited firings: it IS
        # the plan, not a one-shot drill
        default_times = 0 if prob is not None else 1
        specs.append(FaultSpec(point, nth=kw.get("nth"),
                               step=kw.get("step"),
                               times=int(kw.get("times",
                                                default_times)),
                               prob=prob,
                               ms=kw.get("ms"),
                               device=kw.get("device"),
                               leaf=kw.get("leaf"),
                               bit=kw.get("bit")))
    return specs


def _seed_from_env() -> int:
    try:
        from .. import envs
        return int(envs.get("MXTPU_FAULT_SEED"))
    except Exception:
        try:
            return int(os.environ.get("MXTPU_FAULT_SEED", "0"))
        except ValueError:
            return 0


def configure(text: Optional[str], seed: Optional[int] = None) -> int:
    """Install the fault plan from ``text`` (the ``MXTPU_FAULT_INJECT``
    grammar); ``None``/empty clears it.  Returns the spec count.
    Arrival counters, the fired log, and the ``prob=`` RNG (seeded by
    ``seed`` or ``MXTPU_FAULT_SEED``) reset with each configure."""
    global _active, _corrupt_armed
    specs = _parse(text) if text else []
    unknown = [s.point for s in specs if s.point not in POINTS]
    if unknown:
        # unknown points still parse (forward compatibility) but can
        # never fire — a silent typo would make a recovery drill pass
        # vacuously, so say so loudly
        import warnings
        warnings.warn(
            f"MXTPU_FAULT_INJECT: unknown fault point(s) {unknown} "
            f"will never fire (known: {', '.join(POINTS)})",
            RuntimeWarning, stacklevel=2)
    with _lock:
        _specs[:] = specs
        _counts.clear()
        _fired.clear()
        _rng.seed(_seed_from_env() if seed is None else int(seed))
        _active = bool(specs)
        _corrupt_armed = any(s.point in ("corrupt_grad",
                                         "corrupt_wire")
                             for s in specs)
    return len(specs)


def configure_from_env() -> int:
    """(Re-)read ``MXTPU_FAULT_INJECT`` from the environment.

    A malformed spec disables injection with a warning instead of
    raising: this runs at ``import mxnet_tpu``, and a typo'd drill
    knob must never brick every process that imports the library.
    Explicit :func:`configure` calls still raise on bad grammar."""
    try:
        from .. import envs
        text = envs.get("MXTPU_FAULT_INJECT")
    except Exception:
        text = os.environ.get("MXTPU_FAULT_INJECT", "")
    try:
        return configure(text)
    except ValueError as e:
        import warnings
        warnings.warn(
            f"MXTPU_FAULT_INJECT ignored — {e}", RuntimeWarning,
            stacklevel=2)
        configure(None)
        return 0


def clear():
    configure(None)


def active() -> bool:
    """Any un-exhausted fault spec armed?"""
    return _active


def fired() -> List[str]:
    """Repr of every spec that has fired this configuration."""
    with _lock:
        return list(_fired)


def _current_step() -> int:
    try:
        from .. import telemetry
        return telemetry.current_step()
    except Exception:
        return 0


def _check(point: str) -> Optional[FaultSpec]:
    """Count an arrival at ``point``; return the spec that should fire
    now (consuming one of its ``times``), else None."""
    global _active
    with _lock:
        if not _specs:
            return None
        n = _counts.get(point, 0) + 1
        _counts[point] = n
        hit = None
        for s in _specs:
            if s.point != point or s.exhausted:
                continue
            if s.nth is not None and n != s.nth:
                continue
            if s.step is not None and _current_step() < s.step:
                continue
            if s.prob is not None and _rng.random() >= s.prob:
                # the roll happens under the lock, so the RNG stream
                # is a deterministic function of the arrival sequence
                continue
            hit = s
            break
        if hit is not None:
            hit.fired_count += 1
            _fired.append(repr(hit))
        if all(s.exhausted for s in _specs):
            _active = False
        return hit


def _raise(spec: FaultSpec, point: str, **info):
    try:
        from .. import telemetry
        telemetry.record_event("fault_injected", point=point,
                               spec=repr(spec), **info)
        telemetry.counter(
            "mxtpu_faults_injected_total",
            "faults fired by the MXTPU_FAULT_INJECT plan").inc()
    except Exception:
        pass
    raise FaultError(f"injected fault at {point!r} ({spec!r})")


def maybe_fire(point: str, **info):
    """Raise :class:`FaultError` when a spec for ``point`` is due.
    Near-zero when no plan is configured (guard on :data:`_active`
    before calling for the hot paths)."""
    if not _active:
        return
    spec = _check(point)
    if spec is not None:
        _raise(spec, point, **info)


def nonfinite_due(op: str = "") -> bool:
    """The ``nonfinite_grad`` point: unlike the raising points, this
    fault CORRUPTS rather than crashes — when a spec is due the step
    stacks plant a NaN in the input batch (``telemetry.health.
    poison_inputs``), which propagates to a nonfinite loss and
    gradients inside the unchanged compiled program (same shapes, no
    retrace).  The drill that proves the health plane's nonfinite
    detection, skip gate, and rollback end to end.  Returns True when
    the step should poison its inputs."""
    if not _active:
        return False
    spec = _check("nonfinite_grad")
    if spec is None:
        return False
    try:
        from .. import telemetry
        telemetry.record_event("fault_injected", point="nonfinite_grad",
                               spec=repr(spec), op=op)
        telemetry.counter(
            "mxtpu_faults_injected_total",
            "faults fired by the MXTPU_FAULT_INJECT plan").inc()
    except Exception:
        pass
    return True


def preempt_due(where: str = "") -> bool:
    """The ``preempt_signal`` point: like ``nonfinite_grad`` this does
    not raise — when a spec is due the guardian plane's step-owner
    heartbeat (``elastic.guardian``) delivers a REAL ``SIGTERM`` to
    this process, so the installed
    :class:`~.guardian.PreemptionGuard`'s drain path runs exactly as
    it would on a cluster preemption.  Returns True when the signal
    should be sent."""
    if not _active:
        return False
    spec = _check("preempt_signal")
    if spec is None:
        return False
    try:
        from .. import telemetry
        telemetry.record_event("fault_injected", point="preempt_signal",
                               spec=repr(spec), where=where)
        telemetry.counter(
            "mxtpu_faults_injected_total",
            "faults fired by the MXTPU_FAULT_INJECT plan").inc()
    except Exception:
        pass
    return True


def corrupt_armed() -> bool:
    """Is an IN-GRAPH corruption spec (``corrupt_grad`` /
    ``corrupt_wire``) part of the current configuration?  The step
    stacks bake the ctl-driven XOR block while True (their trace
    signature folds this in, so arming/clearing a drill retraces once
    with attribution; production programs are byte-identical when no
    drill is configured).  Sticky across spec exhaustion — see
    :data:`_corrupt_armed`."""
    return _corrupt_armed


def corrupt_due(point: str) -> Optional[Dict[str, int]]:
    """One of the silent-corruption points (``corrupt_param`` /
    ``corrupt_grad`` / ``corrupt_wire``): when a spec is due, returns
    its target payload ``{device, leaf, bit}`` — pinned by the spec's
    ``device=``/``leaf=``/``bit=`` qualifiers, unspecified fields
    drawn from the seeded RNG (same seed + same arrival sequence =
    same targets).  The caller applies the corruption: host buffer
    flip for ``corrupt_param`` (``elastic.integrity.
    corrupt_param_host``), the in-graph ctl vector for the other two
    — and the APPLIER records the one ``fault_injected`` event with
    the CLAMPED values it actually used (the raw draws here may
    exceed the owner's device/leaf counts; see
    :func:`note_corruption_applied`).  Returns ``None`` when nothing
    fires."""
    if not _active:
        return None
    spec = _check(point)
    if spec is None:
        return None
    with _lock:
        payload = {
            "device": int(spec.device) if spec.device is not None
            else _rng.randrange(4096),
            "leaf": int(spec.leaf) if spec.leaf is not None
            else _rng.randrange(4096),
            "bit": int(spec.bit) if spec.bit is not None
            else _rng.randrange(32),
        }
    return payload


def note_corruption_applied(point: str, **applied):
    """The corruption appliers' single telemetry row: ONE
    ``fault_injected`` event per firing, carrying the clamped target
    actually corrupted (``integrity.corrupt_param_host`` /
    ``integrity.ctl_vector`` call it — ``corrupt_due`` itself records
    nothing, so one injection never double-counts)."""
    try:
        from .. import telemetry
        telemetry.record_event("fault_injected", point=point,
                               **applied)
        telemetry.counter(
            "mxtpu_faults_injected_total",
            "faults fired by the MXTPU_FAULT_INJECT plan").inc()
    except Exception:
        pass


def _consume_donated(arrays, donate):
    """Delete the buffers a post-donation drill consumes — exactly the
    set a real TPU executable consuming its donated arguments leaves
    dead (see :func:`on_dispatch` for the ``donate`` contract)."""
    targets = list(arrays) if donate is None else \
        [arrays[i] for i in donate if 0 <= i < len(arrays)]
    for a in targets:
        try:
            a.delete()
        except Exception:
            pass


def on_dispatch(op: str, arrays=(), donate=None):
    """The engine/trainer dispatch hook.

    ``dispatch`` raises with every buffer intact (pre-donation: the
    retry path may transparently re-dispatch).  ``dispatch_post``
    deletes the donated input buffers FIRST — exactly what a TPU
    executable consuming its donated arguments leaves behind — so the
    caller's consumed-probe finds dead buffers and the poison protocol
    engages.  ``dispatch_hang`` sleeps ``ms`` (watchdog-visible: the
    step-owner heartbeat is already open around this call), then
    resolves as a ``dispatch_post`` — a hang that nobody watches still
    terminates into the poison protocol instead of wedging forever.

    ``donate`` selects which ``arrays`` a ``dispatch_post``/
    ``dispatch_hang`` drill consumes: a tuple of indices (the engine
    passes its real donate tuple — an EMPTY tuple means a non-donating
    op, and the drill must not touch buffers the caller still owns),
    or ``None`` when ``arrays`` is already the pre-filtered donated
    set (the SPMD trainer call sites).
    """
    if not _active:
        return
    spec = _check("dispatch")
    if spec is not None:
        _raise(spec, "dispatch", op=op)
    spec = _check("dispatch_post")
    if spec is not None:
        _consume_donated(arrays, donate)
        _raise(spec, "dispatch_post", op=op)
    spec = _check("dispatch_hang")
    if spec is not None:
        import time as _time
        hang_ms = spec.ms if spec.ms is not None else HANG_DEFAULT_MS
        _time.sleep(hang_ms / 1000.0)
        _consume_donated(arrays, donate)
        _raise(spec, "dispatch_hang", op=op, hang_ms=hang_ms)


# arm from the environment at import: fault plans are a process-level
# choice (like MXTPU_ENGINE_TYPE), and reading here keeps the hooks
# free of env lookups
configure_from_env()
