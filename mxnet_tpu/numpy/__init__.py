"""``mx.np`` — NumPy-compatible namespace (SURVEY.md §2.5 "NDArray API":
reference ``python/mxnet/numpy/`` + ``mx.np`` 1.6+).

Semantics differences from ``mx.nd`` (deliberate, matching the
reference's split):
- NumPy dtype PROMOTION (int32+int64→int64, int/2.0→float) instead of
  MXNet's float32-default rules — computed via ``np.result_type``;
- ``array()`` preserves the input's dtype instead of defaulting to f32;
- operators broadcast automatically (mx.nd needs broadcast_* in symbol
  mode).

Every function routes through the op registry/invoke seam, so autograd
records and the per-op jit cache applies — same engine, different
dtype rules (the reference reuses its engine the same way).
"""
from __future__ import annotations

import builtins as _builtins
import functools

import numpy as _onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, invoke
from ..ops.registry import OpDef

__all__ = [
    "array", "zeros", "ones", "full", "empty", "arange", "linspace",
    "eye", "add", "subtract", "multiply", "divide", "true_divide",
    "floor_divide", "mod", "power", "maximum", "minimum", "matmul",
    "dot", "exp", "log", "log2", "log10", "sin", "cos", "tan", "tanh",
    "sinh", "cosh", "arcsin", "arccos", "arctan", "sqrt", "cbrt",
    "abs", "absolute", "negative", "sign", "floor", "ceil", "square",
    "reciprocal", "expm1", "log1p", "sum", "mean", "max", "min",
    "prod", "std", "var", "argmax", "argmin", "reshape", "transpose",
    "expand_dims", "squeeze", "concatenate", "stack", "split", "where",
    "clip", "equal", "not_equal", "less", "less_equal", "greater",
    "greater_equal", "logical_and", "logical_or", "logical_not",
    "tensordot", "einsum", "swapaxes", "moveaxis", "tile", "repeat",
    "broadcast_to", "cumsum",
]


def _jnp():
    import jax.numpy as jnp
    return jnp


# dedicated OpDefs (NOT in the global registry: np semantics must not
# leak into mx.nd/mx.sym name lookup); scalar_ref_input=None so invoke
# never coerces our pre-promoted operands
@functools.lru_cache(maxsize=None)
def _opdef(name: str, n_inputs) -> OpDef:
    fn = getattr(_jnp(), name)
    return OpDef(f"_np_{name}", fn, n_inputs, 1, (), False, None)


def _as_nd(x, dtype=None):
    if isinstance(x, NDArray):
        return x.astype(dtype) if dtype is not None and \
            _onp.dtype(x.dtype) != _onp.dtype(dtype) else x
    a = _onp.asarray(x, dtype=dtype)
    return NDArray.from_numpy(a) if hasattr(NDArray, "from_numpy") \
        else _from_np(a)


def _from_np(a):
    from ..ndarray import ndarray as nd_mod
    return nd_mod.array(a, dtype=a.dtype)


def _promote(*xs):
    """NumPy-rules common dtype across NDArray and python operands.

    Without MXTPU_ENABLE_X64, 64-bit promotion targets clamp to their
    32-bit widths (what JAX would silently truncate to anyway)."""
    parts = []
    for x in xs:
        if isinstance(x, NDArray):
            parts.append(_onp.dtype(x.dtype))
        else:
            parts.append(x if _onp.isscalar(x) else _onp.asarray(x))
    rt = _onp.result_type(*parts)
    if not _np_x64():
        rt = {_onp.dtype("float64"): _onp.dtype("float32"),
              _onp.dtype("int64"): _onp.dtype("int32"),
              _onp.dtype("uint64"): _onp.dtype("uint32"),
              _onp.dtype("complex128"): _onp.dtype("complex64"),
              }.get(rt, rt)
    return [_as_nd(x, dtype=rt) for x in xs], rt


def _float_dtype():
    """Default float width under the current x64 setting."""
    return "float64" if _np_x64() else "float32"


def _unary(name):
    def f(x, *args, **kw):
        x = _as_nd(x)
        if args:
            # NumPy callers pass the 2nd+ arguments positionally
            # (np.roll(a, 1), np.tile(a, reps)); map them onto the jnp
            # function's parameter names so invoke sees attrs
            import inspect
            params = [p.name for p in inspect.signature(
                getattr(_jnp(), name)).parameters.values()][1:]
            kw.update(dict(zip(params, args)))
        return invoke(_opdef(name, 1), [x], **kw)
    f.__name__ = name
    f.__doc__ = f"NumPy-semantics {name} (see numpy.{name})."
    return f


def _unary_float(name):
    """Unary transcendental: ints promote to float64 (NumPy rule)."""
    def f(x, **kw):
        x = _as_nd(x)
        if _onp.dtype(x.dtype).kind in "iub":
            x = x.astype(_float_dtype())
        return invoke(_opdef(name, 1), [x], **kw)
    f.__name__ = name
    f.__doc__ = f"NumPy-semantics {name} (see numpy.{name})."
    return f


def _np_x64():
    import jax
    return bool(jax.config.read("jax_enable_x64"))


def _binary(name, promote=True):
    def f(a, b, **kw):
        if promote:
            (a, b), _ = _promote(a, b)
        else:
            a, b = _as_nd(a), _as_nd(b)
        return invoke(_opdef(name, 2), [a, b], **kw)
    f.__name__ = name
    f.__doc__ = f"NumPy-semantics {name} (see numpy.{name})."
    return f


@functools.lru_cache(maxsize=None)
def _opdef_variadic(name: str) -> OpDef:
    jf = getattr(_jnp(), name)

    def fc(*arrays, **kw):
        # jnp.concatenate/stack take ONE sequence argument
        return jf(list(arrays), **kw)

    return OpDef(f"_np_{name}", fc, None, 1, (), False, None)


def _variadic(name):
    def f(arrays, **kw):
        arrays = [_as_nd(a) for a in arrays]
        return invoke(_opdef_variadic(name), list(arrays), **kw)
    f.__name__ = name
    f.__doc__ = f"NumPy-semantics {name} (see numpy.{name})."
    return f


# -- creation ---------------------------------------------------------------

def array(obj, dtype=None, ctx=None):
    """np.array parity: PRESERVES the input dtype (mx.nd defaults f32)."""
    a = _onp.asarray(obj, dtype=dtype)
    from ..ndarray import ndarray as nd_mod
    return nd_mod.array(a, ctx=ctx, dtype=a.dtype)


def zeros(shape, dtype="float32", ctx=None):
    from ..ndarray import ndarray as nd_mod
    return nd_mod.zeros(shape, ctx=ctx, dtype=dtype)


def ones(shape, dtype="float32", ctx=None):
    from ..ndarray import ndarray as nd_mod
    return nd_mod.ones(shape, ctx=ctx, dtype=dtype)


def full(shape, fill_value, dtype=None, ctx=None):
    if dtype is None:
        dtype = _onp.result_type(fill_value)
    return array(_onp.full(shape, fill_value, dtype=dtype), ctx=ctx)


def empty(shape, dtype="float32", ctx=None):
    return zeros(shape, dtype=dtype, ctx=ctx)


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    return array(_onp.arange(start, stop, step, dtype=dtype), ctx=ctx)


def linspace(start, stop, num=50, endpoint=True, dtype=None, ctx=None):
    return array(_onp.linspace(start, stop, num, endpoint=endpoint,
                               dtype=dtype), ctx=ctx)


def eye(N, M=None, k=0, dtype="float32", ctx=None):
    return array(_onp.eye(N, M, k, dtype=dtype), ctx=ctx)


# -- arithmetic (NumPy promotion) -------------------------------------------

add = _binary("add")
subtract = _binary("subtract")
multiply = _binary("multiply")
power = _binary("power")
maximum = _binary("maximum")
minimum = _binary("minimum")
mod = _binary("mod")
floor_divide = _binary("floor_divide")
matmul = _binary("matmul", promote=False)
dot = _binary("dot", promote=False)
arctan2 = _binary("arctan2")
hypot = _binary("hypot")


def divide(a, b, **kw):
    """NumPy true division: integer inputs produce float output."""
    (a, b), rt = _promote(a, b)
    if _onp.dtype(rt).kind in "iub":
        a, b = a.astype(_float_dtype()), b.astype(_float_dtype())
    return invoke(_opdef("divide", 2), [a, b], **kw)


true_divide = divide

equal = _binary("equal")
not_equal = _binary("not_equal")
less = _binary("less")
less_equal = _binary("less_equal")
greater = _binary("greater")
greater_equal = _binary("greater_equal")
logical_and = _binary("logical_and")
logical_or = _binary("logical_or")
logical_not = _unary("logical_not")

# -- elementwise ------------------------------------------------------------

exp = _unary_float("exp")
log = _unary_float("log")
log2 = _unary_float("log2")
log10 = _unary_float("log10")
log1p = _unary_float("log1p")
expm1 = _unary_float("expm1")
sin = _unary_float("sin")
cos = _unary_float("cos")
tan = _unary_float("tan")
tanh = _unary_float("tanh")
sinh = _unary_float("sinh")
cosh = _unary_float("cosh")
arcsin = _unary_float("arcsin")
arccos = _unary_float("arccos")
arctan = _unary_float("arctan")
sqrt = _unary_float("sqrt")
cbrt = _unary_float("cbrt")
reciprocal = _unary_float("reciprocal")
abs = _unary("abs")
absolute = abs
negative = _unary("negative")
sign = _unary("sign")
floor = _unary("floor")
ceil = _unary("ceil")
square = _unary("square")

# -- reductions -------------------------------------------------------------

sum = _unary("sum")
mean = _unary("mean")
max = _unary("max")
min = _unary("min")
prod = _unary("prod")
std = _unary("std")
var = _unary("var")
argmax = _unary("argmax")
argmin = _unary("argmin")
cumsum = _unary("cumsum")

# -- shape ------------------------------------------------------------------

reshape = _unary("reshape")
transpose = _unary("transpose")
expand_dims = _unary("expand_dims")
squeeze = _unary("squeeze")
swapaxes = _unary("swapaxes")
moveaxis = _unary("moveaxis")
tile = _unary("tile")
repeat = _unary("repeat")
broadcast_to = _unary("broadcast_to")
clip = _unary("clip")

concatenate = _variadic("concatenate")
stack = _variadic("stack")


def split(x, indices_or_sections, axis=0):
    x = _as_nd(x)
    jnp = _jnp()
    parts = jnp.split(x._data, indices_or_sections, axis=axis)
    return [NDArray(p, ctx=x._ctx) for p in parts]


def where(cond, a, b):
    cond = _as_nd(cond)
    (a, b), _ = _promote(a, b)
    return invoke(_opdef("where", 3), [cond, a, b])


def tensordot(a, b, axes=2):
    a, b = _as_nd(a), _as_nd(b)
    return invoke(_opdef("tensordot", 2), [a, b], axes=axes)


@functools.lru_cache(maxsize=None)
def _opdef_einsum():
    jnp = _jnp()

    def fc(*arrays, subscripts):
        return jnp.einsum(subscripts, *arrays)

    return OpDef("_np_einsum", fc, None, 1, (), False, None)


def einsum(subscripts, *operands):
    """Routed through the invoke seam so autograd records it (a direct
    jnp call here once produced silent zero grads under record())."""
    ops = [_as_nd(o) for o in operands]
    return invoke(_opdef_einsum(), ops, subscripts=subscripts)


# -- sorting / indexing -----------------------------------------------------

sort = _unary("sort")
argsort = _unary("argsort")
flip = _unary("flip")
roll = _unary("roll")
ravel = _unary("ravel")
diag = _unary("diag")
tril = _unary("tril")
triu = _unary("triu")
trace = _unary("trace")
cumprod = _unary("cumprod")
round = _unary("round")
around = round
trunc = _unary("trunc")
rint = _unary("rint")
isnan = _unary("isnan")
isinf = _unary("isinf")
isfinite = _unary("isfinite")
all = _unary("all")
any = _unary("any")
diff = _unary("diff")
nan_to_num = _unary("nan_to_num")
exp2 = _unary_float("exp2")
deg2rad = _unary_float("deg2rad")
rad2deg = _unary_float("rad2deg")
median = _unary("median")
count_nonzero = _unary("count_nonzero")

outer = _binary("outer", promote=False)
inner = _binary("inner", promote=False)
kron = _binary("kron", promote=False)
cross = _binary("cross", promote=False)
vdot = _binary("vdot", promote=False)


def take(a, indices, axis=None, mode="clip"):
    a, indices = _as_nd(a), _as_nd(indices)
    return invoke(_opdef("take", 2), [a, indices], axis=axis,
                  mode=mode)


def quantile(a, q, axis=None):
    a = _as_nd(a)
    return invoke(_opdef("quantile", 2), [a, _as_nd(q)], axis=axis)


def percentile(a, q, axis=None):
    return quantile(a, _onp.asarray(q, dtype=_float_dtype()) / 100.0,
                    axis=axis)


def meshgrid(*xs, indexing="xy"):
    xs = [_as_nd(x) for x in xs]
    jnp = _jnp()
    outs = jnp.meshgrid(*[x._data for x in xs], indexing=indexing)
    return [NDArray(o, ctx=xs[0]._ctx) for o in outs]


def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    (a, b), _ = _promote(a, b)
    return bool(_onp.allclose(a.asnumpy(), b.asnumpy(), rtol=rtol,
                              atol=atol, equal_nan=equal_nan))


def array_equal(a, b):
    (a, b), _ = _promote(a, b)
    return bool(_onp.array_equal(a.asnumpy(), b.asnumpy()))


# -- np.linalg --------------------------------------------------------------


class _Linalg:
    """``mx.np.linalg`` — NumPy-semantics linear algebra over XLA
    (reference: mxnet.numpy.linalg)."""

    @functools.lru_cache(maxsize=None)
    def _op(self, name, n_out=1, n_in=1):
        import jax.numpy as jnp
        fn = getattr(jnp.linalg, name)
        return OpDef(f"_np_linalg_{name}", fn, n_in, n_out, (), False,
                     None)

    def _call(self, name, x, n_out=1, **kw):
        x = _as_nd(x)
        if _onp.dtype(x.dtype).kind in "iub":
            x = x.astype(_float_dtype())
        return invoke(self._op(name, n_out), [x], **kw)

    def norm(self, x, ord=None, axis=None, keepdims=False):
        return self._call("norm", x, ord=ord, axis=axis,
                          keepdims=keepdims)

    def inv(self, x):
        return self._call("inv", x)

    def det(self, x):
        return self._call("det", x)

    def cholesky(self, x):
        return self._call("cholesky", x)

    def svd(self, x):
        return self._call("svd", x, n_out=3)

    def qr(self, x):
        return self._call("qr", x, n_out=2)

    def eigh(self, x):
        return self._call("eigh", x, n_out=2)

    def slogdet(self, x):
        return self._call("slogdet", x, n_out=2)

    def solve(self, a, b):
        (a, b), rt = _promote(a, b)
        if _onp.dtype(rt).kind in "iub":
            a = a.astype(_float_dtype())
            b = b.astype(_float_dtype())
        return invoke(self._op("solve", n_in=2), [a, b])

    def lstsq(self, a, b, rcond=None):
        import jax.numpy as jnp
        a, b = _as_nd(a), _as_nd(b)
        outs = jnp.linalg.lstsq(a._data, b._data, rcond=rcond)
        return tuple(NDArray(o, ctx=a._ctx) for o in outs)

    def matrix_rank(self, x):
        return self._call("matrix_rank", x)


linalg = _Linalg()


# -- np.random --------------------------------------------------------------


class _NpRandom:
    """``mx.np.random`` — numpy-style RNG over the counter-based key
    stream (reference: mxnet.numpy.random; same seed machinery as
    mx.random)."""

    @staticmethod
    def _mx_random():
        from .. import random as mxrand
        return mxrand

    def seed(self, s):
        self._mx_random().seed(s)

    def uniform(self, low=0.0, high=1.0, size=None, dtype="float32",
                ctx=None):
        return self._mx_random().uniform(
            low, high, shape=() if size is None else size,
            dtype=dtype, ctx=ctx)

    def normal(self, loc=0.0, scale=1.0, size=None, dtype="float32",
               ctx=None):
        return self._mx_random().normal(
            loc, scale, shape=() if size is None else size,
            dtype=dtype, ctx=ctx)

    def randint(self, low, high=None, size=None, dtype="int32",
                ctx=None):
        if high is None:
            low, high = 0, low
        return self._mx_random().randint(
            low, high, shape=() if size is None else size,
            dtype=dtype, ctx=ctx)

    def rand(self, *shape):
        return self.uniform(size=shape)

    def randn(self, *shape):
        return self.normal(size=shape)

    def choice(self, a, size=None, replace=True, p=None):
        mxr = self._mx_random()
        if isinstance(a, int):
            if p is None and replace:
                return self.randint(0, a, size=size)
            a = arange(a)
        a = _as_nd(a)
        n = a.shape[0]
        if not replace:
            if p is not None:
                raise MXNetError(
                    "np.random.choice: replace=False with "
                    "probabilities is not supported")
            k = 1 if size is None else int(_onp.prod(size))
            if k > n:
                raise MXNetError(
                    f"cannot take {k} unique samples from a "
                    f"population of {n}")
            perm = mxr.shuffle(arange(n))
            idx = perm[0:k]
            out = take(a, idx, axis=0)
            return out if size is None else out.reshape(
                (size,) if isinstance(size, int) else tuple(size))
        if p is None:
            idx = self.randint(0, n, size=size)
            return take(a, idx, axis=0)
        p = _as_nd(p)
        idx = invoke(_opdef_multinomial(), [mxr._next_key_nd(a._ctx), p],
                     shape=() if size is None else tuple(
                         (size,) if isinstance(size, int) else size))
        return take(a, idx, axis=0)

    def shuffle(self, x):
        """In-place shuffle along axis 0 (numpy.random.shuffle
        contract)."""
        self._mx_random().shuffle(x, out=x)


@functools.lru_cache(maxsize=None)
def _opdef_multinomial():
    from ..ops.registry import get_op
    return get_op("_sample_multinomial")


random = _NpRandom()


# -- array manipulation / statistics tail (reference mx.np parity) ----------


def pad(a, pad_width, mode="constant", **kw):
    a = _as_nd(a)
    return invoke(_opdef("pad", 1), [a], pad_width=_tupled(pad_width),
                  mode=mode, **kw)


def _tupled(pw):
    """jnp.pad wants hashable static pad_width for the jit cache."""
    if isinstance(pw, int):
        return pw
    return tuple(tuple(p) if isinstance(p, (list, tuple)) else p
                 for p in pw)


def searchsorted(a, v, side="left"):
    a, v = _as_nd(a), _as_nd(v)
    return invoke(_opdef("searchsorted", 2), [a, v], side=side)


def cov(m, rowvar=True, bias=False, ddof=None):
    m = _as_nd(m)
    if _onp.dtype(m.dtype).kind in "iub":
        m = m.astype(_float_dtype())
    return invoke(_opdef("cov", 1), [m], rowvar=rowvar, bias=bias,
                  ddof=ddof)


def corrcoef(x, rowvar=True):
    x = _as_nd(x)
    if _onp.dtype(x.dtype).kind in "iub":
        x = x.astype(_float_dtype())
    return invoke(_opdef("corrcoef", 1), [x], rowvar=rowvar)


def interp(x, xp, fp, left=None, right=None):
    x, xp, fp = _as_nd(x), _as_nd(xp), _as_nd(fp)
    return invoke(_opdef("interp", 3), [x, xp, fp], left=left,
                  right=right)


@functools.lru_cache(maxsize=None)
def _opdef_gradient(n_out):
    jnp = _jnp()

    def fc(f, *spacing, axis=None):
        out = jnp.gradient(f, *spacing, axis=axis)
        return tuple(out) if isinstance(out, (list, tuple)) else out

    return OpDef("_np_gradient", fc, None, n_out, (), False, None)


def gradient(f, *varargs, axis=None):
    f = _as_nd(f)
    axes = (axis if axis is not None
            else tuple(range(f.ndim)) if f.ndim > 1 else 0)
    n_out = len(axes) if isinstance(axes, (tuple, list)) else 1
    spacing = [_as_nd(v) for v in varargs]
    out = invoke(_opdef_gradient(n_out), [f, *spacing], axis=axis)
    return list(out) if isinstance(out, (list, tuple)) else out


@functools.lru_cache(maxsize=None)
def _opdef_histogram():
    jnp = _jnp()

    def fc(*arrays, bins, range, has_bins_arr, has_w):
        it = iter(arrays)
        a = next(it)
        b = next(it) if has_bins_arr else bins
        w = next(it) if has_w else None
        return jnp.histogram(a, bins=b, range=range, weights=w)

    return OpDef("_np_histogram", fc, None, 2, (), False, None)


def histogram(a, bins=10, range=None, weights=None):
    """Static-shape when ``bins`` is an int (jit-friendly); returns
    (hist, bin_edges) like numpy.  Routed through the invoke seam like
    every other function here (engine sync, profiler, NaiveEngine)."""
    a = _as_nd(a)
    inputs = [a]
    if isinstance(bins, NDArray):
        inputs.append(bins)
        bins_attr = None
    else:
        bins_attr = bins
    if weights is not None:
        inputs.append(_as_nd(weights))
    hist, edges = invoke(_opdef_histogram(), inputs, bins=bins_attr,
                         range=range,
                         has_bins_arr=isinstance(bins, NDArray),
                         has_w=weights is not None)
    return hist, edges


def unique(a, return_index=False, return_inverse=False,
           return_counts=False):
    """Data-dependent output shape → computed on host (sync point),
    like the reference's CPU fallback for dynamic-shape ops."""
    a = _as_nd(a)
    out = _onp.unique(a.asnumpy(), return_index=return_index,
                      return_inverse=return_inverse,
                      return_counts=return_counts)
    if isinstance(out, tuple):
        return tuple(_from_np(o) for o in out)
    return _from_np(out)


# nan-aware reductions + misc numpy tail, all registry-routed
nansum = _unary("nansum")
nanmean = _unary("nanmean")
nanmax = _unary("nanmax")
nanmin = _unary("nanmin")
nanstd = _unary("nanstd")
nanvar = _unary("nanvar")
ptp = _unary("ptp")
real = _unary("real")
imag = _unary("imag")
conj = _unary("conj")
conjugate = conj
angle = _unary("angle")
digitize = _binary("digitize", promote=False)


def trapz(y, x=None, dx=1.0, axis=-1):
    """numpy.trapezoid contract: optional sample positions ``x`` ride
    as a tensor INPUT (an attr would hand a raw NDArray to jax)."""
    y = _as_nd(y)
    if x is None:
        return invoke(_opdef("trapezoid", 1), [y], dx=dx, axis=axis)
    return invoke(_opdef_trapz_x(), [y, _as_nd(x)], axis=axis)


@functools.lru_cache(maxsize=None)
def _opdef_trapz_x():
    jnp = _jnp()

    def fc(y, x, axis):
        return jnp.trapezoid(y, x, axis=axis)

    return OpDef("_np_trapz_x", fc, 2, 1, (), False, None)


def ediff1d(ary, to_end=None, to_begin=None):
    ary = _as_nd(ary)
    inputs = [ary]
    if to_end is not None:
        inputs.append(_as_nd(to_end))
    if to_begin is not None:
        inputs.append(_as_nd(to_begin))
    return invoke(_opdef_ediff1d(), inputs,
                  has_end=to_end is not None,
                  has_begin=to_begin is not None)


@functools.lru_cache(maxsize=None)
def _opdef_ediff1d():
    jnp = _jnp()

    def fc(*arrays, has_end, has_begin):
        it = iter(arrays)
        a = next(it)
        end = next(it) if has_end else None
        begin = next(it) if has_begin else None
        return jnp.ediff1d(a, to_end=end, to_begin=begin)

    return OpDef("_np_ediff1d", fc, None, 1, (), False, None)


def average(a, axis=None, weights=None):
    a = _as_nd(a)
    if weights is None:
        return invoke(_opdef("mean", 1), [a], axis=axis)
    w = _as_nd(weights)
    return invoke(_opdef_average(), [a, w], axis=axis)


@functools.lru_cache(maxsize=None)
def _opdef_average():
    jnp = _jnp()

    def fc(a, w, axis):
        return jnp.average(a, axis=axis, weights=w)

    return OpDef("_np_average", fc, 2, 1, (), False, None)


def bincount(x, weights=None, minlength=0):
    """Static-shape when ``minlength`` covers the value range; like
    jnp, values >= the output length are dropped.  Computed with
    length = max(minlength, host max+1) — a sync point, matching the
    reference's dynamic-shape ops."""
    x = _as_nd(x)
    host = _onp.asarray(x.asnumpy())
    # numpy contract: negatives are an error, floats must be integral
    # (silent clipping/truncation would fabricate plausible counts)
    if host.size and host.min() < 0:
        raise ValueError("bincount: input must be non-negative")
    if host.dtype.kind == "f" and not _onp.equal(
            _onp.mod(host, 1), 0).all():
        raise TypeError("bincount: input must hold integral values")
    # NB: plain `max` here would resolve to this module's np.max
    length = _builtins.max(
        int(minlength), int(host.max(initial=-1)) + 1)
    inputs = [x]
    if weights is not None:
        inputs.append(_as_nd(weights))
    return invoke(_opdef_bincount(), inputs, length=length,
                  has_w=weights is not None)


@functools.lru_cache(maxsize=None)
def _opdef_bincount():
    jnp = _jnp()

    def fc(*arrays, length, has_w):
        w = arrays[1] if has_w else None
        return jnp.bincount(arrays[0].astype(jnp.int32), weights=w,
                            length=length)

    return OpDef("_np_bincount", fc, None, 1, (), False, None)


def nonzero(a):
    """Dynamic output shape → host fallback (sync point)."""
    a = _as_nd(a)
    return tuple(_from_np(i) for i in _onp.nonzero(a.asnumpy()))


def argwhere(a):
    a = _as_nd(a)
    return _from_np(_onp.argwhere(a.asnumpy()))


def flatnonzero(a):
    a = _as_nd(a)
    return _from_np(_onp.flatnonzero(a.asnumpy()))


class _Fft:
    """``mx.np.fft`` — FFT family over XLA (complex64 under the
    default x64-off config; parity: numpy.fft's interface)."""

    @functools.lru_cache(maxsize=None)
    def _op(self, name, n_in=1):
        import jax.numpy as jnp
        fn = getattr(jnp.fft, name)
        return OpDef(f"_np_fft_{name}", fn, n_in, 1, (), False, None)

    def _call(self, name, x, **kw):
        x = _as_nd(x)
        if _onp.dtype(x.dtype).kind in "iub":
            x = x.astype(_float_dtype())
        return invoke(self._op(name), [x], **kw)

    def fft(self, a, n=None, axis=-1):
        return self._call("fft", a, n=n, axis=axis)

    def ifft(self, a, n=None, axis=-1):
        return self._call("ifft", a, n=n, axis=axis)

    def rfft(self, a, n=None, axis=-1):
        return self._call("rfft", a, n=n, axis=axis)

    def irfft(self, a, n=None, axis=-1):
        return self._call("irfft", a, n=n, axis=axis)

    def fft2(self, a, axes=(-2, -1)):
        return self._call("fft2", a, axes=tuple(axes))

    def ifft2(self, a, axes=(-2, -1)):
        return self._call("ifft2", a, axes=tuple(axes))

    def fftn(self, a, axes=None):
        return self._call("fftn", a,
                          axes=None if axes is None else tuple(axes))

    def ifftn(self, a, axes=None):
        return self._call("ifftn", a,
                          axes=None if axes is None else tuple(axes))

    def fftshift(self, a, axes=None):
        return self._call("fftshift", a,
                          axes=None if axes is None else tuple(axes))

    def ifftshift(self, a, axes=None):
        return self._call("ifftshift", a,
                          axes=None if axes is None else tuple(axes))

    def fftfreq(self, n, d=1.0):
        import jax.numpy as jnp
        return _from_np(_onp.asarray(jnp.fft.fftfreq(n, d=d)))

    def rfftfreq(self, n, d=1.0):
        import jax.numpy as jnp
        return _from_np(_onp.asarray(jnp.fft.rfftfreq(n, d=d)))


fft = _Fft()

__all__ += ["pad", "searchsorted", "cov", "corrcoef", "interp",
            "gradient", "histogram", "unique", "fft",
            "nansum", "nanmean", "nanmax", "nanmin", "nanstd",
            "nanvar", "ptp", "ediff1d", "real", "imag", "conj",
            "conjugate", "angle", "digitize", "trapz", "average",
            "bincount", "nonzero", "argwhere", "flatnonzero"]

__all__ += ["sort", "argsort", "flip", "roll", "ravel", "diag", "tril",
            "triu", "trace", "cumprod", "round", "around", "trunc",
            "rint", "isnan", "isinf", "isfinite", "all", "any", "diff",
            "nan_to_num", "exp2", "deg2rad", "rad2deg", "median",
            "count_nonzero", "outer", "inner", "kron", "cross", "vdot",
            "take", "quantile", "percentile", "meshgrid", "allclose",
            "array_equal", "linalg", "random"]
