"""Neural-network operators.

Capability parity: reference ``src/operator/nn/`` (convolution, pooling,
fully_connected, activation, batch_norm, layer_norm, dropout, softmax,
deconvolution, ...) — SURVEY.md §2.2.  The reference keeps a generic mshadow
implementation plus cuDNN/oneDNN fast paths per op; here each op is one pure
JAX function and XLA supplies the fast path (MXU matmuls/convs, fused
elementwise).  Layout is MXNet's NCHW/OIHW API-side; XLA is free to relayout
internally for the MXU.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register, alias
from .tensor import _int8_acc

# ---------------------------------------------------------------------------
# fully connected / dense — reference fully_connected.cc
# ---------------------------------------------------------------------------


@register("FullyConnected", num_inputs=None)
def fully_connected(data, weight, *rest, num_hidden=0, no_bias=False,
                    flatten=True):
    """y = x @ W.T + b.  weight shape (num_hidden, in_units)."""
    if flatten and data.ndim > 2:
        data = jnp.reshape(data, (data.shape[0], -1))
    out = jnp.matmul(data, weight.T)
    if not no_bias:
        out = out + rest[0]
    return out


# ---------------------------------------------------------------------------
# activations — reference activation.cc, leaky_relu.cc
# ---------------------------------------------------------------------------


@register("Activation")
def activation(data, *, act_type="relu"):
    fns = {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid,
           "tanh": jnp.tanh, "softrelu": jax.nn.softplus,
           "softsign": jax.nn.soft_sign, "log_sigmoid": jax.nn.log_sigmoid,
           "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
           "relu6": lambda x: jnp.clip(x, 0.0, 6.0)}
    return fns[act_type](data)


@register("LeakyReLU", num_inputs=None)
def leaky_relu(data, *rest, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * (jnp.exp(data) - 1.0))
    if act_type == "selu":
        a, s = 1.6732632423543772, 1.0507009873554805
        return s * jnp.where(data > 0, data, a * (jnp.exp(data) - 1.0))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "prelu":
        gamma = rest[0]
        g = jnp.reshape(gamma, (1, -1) + (1,) * (data.ndim - 2)) \
            if data.ndim > 1 and gamma.size > 1 else gamma
        return jnp.where(data > 0, data, g * data)
    if act_type == "rrelu":
        # eval-mode rrelu uses the mean slope (train-mode randomness is
        # handled by the Dropout-style keyed variant upstream in gluon)
        slope_m = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, slope_m * data)
    raise ValueError(f"unknown act_type {act_type}")


@register("gelu_tanh")
def gelu_tanh(data):
    return jax.nn.gelu(data, approximate=True)


@register("silu")
def silu(data):
    return jax.nn.silu(data)


# ---------------------------------------------------------------------------
# softmax family — reference softmax.cc, softmax_output.cc
# ---------------------------------------------------------------------------


@register("softmax", num_inputs=None)
def softmax(data, *rest, axis=-1, temperature=None, use_length=False):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    if use_length and rest:
        length = rest[0].astype("int32")
        steps = jnp.arange(data.shape[axis])
        shape = [1] * data.ndim
        shape[axis] = data.shape[axis]
        mask = jnp.reshape(steps, shape) < jnp.expand_dims(length, axis)
        data = jnp.where(mask, data, -jnp.inf)
        out = jax.nn.softmax(data, axis=axis)
        return jnp.where(mask, out, 0.0)
    return jax.nn.softmax(data, axis=axis)


@register("log_softmax")
def log_softmax(data, *, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.log_softmax(data, axis=axis)


@register("softmin")
def softmin(data, *, axis=-1):
    return jax.nn.softmax(-data, axis=axis)


@register("SoftmaxActivation")
def softmax_activation(data, *, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1) \
        .reshape(data.shape)


@register("SoftmaxOutput", num_inputs=2)
def softmax_output(data, label, *, grad_scale=1.0, ignore_label=-1.0,
                   use_ignore=False, multi_output=False,
                   preserve_shape=False, normalization="null",
                   out_grad=False, smooth_alpha=0.0):
    """Legacy fused softmax+CE-grad op (reference softmax_output.cc).

    Forward emits softmax probabilities; the BACKWARD is the implicit
    cross-entropy gradient ``(prob - one_hot(label)) * grad_scale`` — NOT
    the softmax Jacobian — wired via jax.custom_vjp so Module/Executor
    training loops behave exactly like the reference (loss comes for free
    from the head op, no explicit loss node).
    """
    axis = 1 if multi_output else -1

    @jax.custom_vjp
    def _f(d, l):
        return jax.nn.softmax(d, axis=axis)

    def _fwd(d, l):
        prob = jax.nn.softmax(d, axis=axis)
        return prob, (prob, l)

    def _bwd(res, g):
        prob, l = res
        k = prob.shape[axis]
        li = l.astype("int32")
        onehot = jax.nn.one_hot(li, k, axis=axis, dtype=prob.dtype)
        if smooth_alpha:
            onehot = onehot * (1.0 - smooth_alpha) + smooth_alpha / k
        grad = prob - onehot
        if use_ignore:
            mask = (li != int(ignore_label)).astype(prob.dtype)
            grad = grad * jnp.expand_dims(mask, axis=axis)
        scale = grad_scale
        if normalization == "batch":
            grad = grad / prob.shape[0]
        elif normalization == "valid":
            if use_ignore:
                nvalid = jnp.maximum(
                    (li != int(ignore_label)).sum().astype(prob.dtype), 1.0)
            else:
                nvalid = float(np.prod(l.shape))
            grad = grad / nvalid
        grad = grad * scale
        if out_grad:
            grad = grad * g
        return grad, jnp.zeros_like(l)

    _f.defvjp(_fwd, _bwd)
    return _f(data, label)


@register("softmax_cross_entropy", num_inputs=2)
def softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    lbl = label.astype("int32")
    picked = jnp.take_along_axis(logp, lbl[:, None], axis=-1)
    return -jnp.sum(picked)


@register("chunked_softmax_ce_bias", num_inputs=4)
def chunked_softmax_ce_bias(hidden, weight, bias, label, *, chunk=8192,
                            axis_name=None):
    """:func:`chunked_softmax_ce` with a per-vocab-row logit bias —
    the BERT-style tied decode (``h @ Wᵀ + b``); the bias streams
    through the same slabs and receives gradients (it is the THIRD
    tape input — num_inputs=4 — so ``b.grad`` is real).  Under
    ``axis_name`` (tp mode) pass this rank's bias shard (V/tp,)."""
    return _chunked_ce_impl(hidden, weight, label, bias=bias,
                            chunk=chunk, axis_name=axis_name)


@register("chunked_softmax_ce", num_inputs=3)
def chunked_softmax_ce(hidden, weight, label, *, chunk=8192,
                       axis_name=None):
    """Streaming large-vocab cross-entropy: per-row
    ``logsumexp(h @ Wᵀ) - (h @ Wᵀ)[label]`` WITHOUT materializing the
    (N, V) logits.  THE entry point for large-vocab CE; the dispatch
    rule is:

    * ``axis_name=None`` (default): ``weight`` is the FULL (V, U)
      matrix on this device; the scan streams it in slabs.
    * ``axis_name='tp'`` (inside ``shard_map``): ``weight`` is this
      rank's vocab shard (V/tp, U), ranks tiling rows in order — the
      SAME slab scan runs inside each shard and the global normalizer
      and label logit are assembled Megatron-style with one ``pmax`` +
      one fused ``psum`` (the composition VERDICT r4 #4 asked for:
      tp × huge-vocab keeps BOTH the sharded head and the O(N·chunk)
      activation bound).
      ``parallel.collectives.vocab_parallel_softmax_ce`` is the
      single-slab (``chunk >= V/tp``) specialization of this path.

    The reference (and the naive ``loss`` path) computes full logits
    then softmax CE — at Llama-3-8B vocab (128256), batch 8 × seq 4096
    that is a 16.8 GB f32 activation, over a v5e's entire HBM.  Here a
    ``lax.scan`` walks W in (chunk, U) slabs keeping only the running
    (max, sumexp, label-logit) carry, and ``jax.checkpoint`` on the
    slab body makes the BACKWARD recompute each slab's logits instead
    of saving them — peak activation O(N·chunk), compute unchanged
    (one extra fwd pass for the remat, the standard trade).

    hidden (N, U); weight (V, U) — the tied embedding or LM-head
    matrix (gradients flow to both inputs); label (N,) int, GLOBAL
    vocab ids in both modes.  For a per-vocab logit bias (BERT tied
    decode) use :func:`chunked_softmax_ce_bias` — bias is
    deliberately NOT a kwarg here: on the registered 3-input op a
    keyword tensor would ride the static-attr path and silently drop
    its gradient.  Returns per-row loss (N,), f32.
    """
    return _chunked_ce_impl(hidden, weight, label, bias=None,
                            chunk=chunk, axis_name=axis_name)


def _chunked_ce_impl(hidden, weight, label, *, bias, chunk, axis_name):
    n, u = hidden.shape
    v = weight.shape[0]
    chunk = int(min(chunk, v))
    n_chunks = -(-v // chunk)
    # re-balance so the slabs tile V with minimal padding: the naive
    # ceil split pads up to chunk-1 rows (2816 at Llama-3's 128256 /
    # 8192 — a ~2 GB padded weight copy each step); ceil(v/n_chunks)
    # pads < n_chunks rows (usually 0: 128256 → 16 slabs of 8016)
    chunk = -(-v // n_chunks)
    pad = n_chunks * chunk - v
    w = jnp.pad(weight, ((0, pad), (0, 0))) if pad else weight
    w = w.reshape(n_chunks, chunk, u)
    has_bias = bias is not None
    if has_bias:
        bvec = bias.astype(jnp.float32)
        bvec = jnp.pad(bvec, (0, pad)) if pad else bvec
        bslabs = bvec.reshape(n_chunks, chunk)
    lbl = label.astype(jnp.int32)
    if axis_name is not None:
        # weight is this rank's vocab shard: translate the GLOBAL
        # labels into shard-local row ids (out-of-shard labels fall
        # outside every slab's range and contribute an exact zero)
        lbl = lbl - lax.axis_index(axis_name) * jnp.int32(v)

    @jax.checkpoint
    def slab(carry, wc_i):
        m, s, lab = carry
        if has_bias:
            wc, bc, i = wc_i
        else:
            wc, i = wc_i
        logits = jnp.dot(hidden, wc.T,
                         preferred_element_type=jnp.float32)
        if has_bias:
            logits = logits + bc[None, :]
        if pad:
            # padded vocab rows must not enter the normalizer
            col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
            logits = jnp.where(i * chunk + col < v, logits, -jnp.inf)
        m_new = jnp.maximum(m, logits.max(axis=1))
        s = s * jnp.exp(m - m_new) + jnp.exp(
            logits - m_new[:, None]).sum(axis=1)
        idx = lbl - i * chunk
        in_range = (idx >= 0) & (idx < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, chunk - 1)[:, None],
            axis=1)[:, 0]
        lab = lab + jnp.where(in_range, picked, 0.0)
        return (m_new, s, lab), None

    # tie the init carry's device-varying type to the inputs: under
    # shard_map (pipeline/tensor parallel callers) the loop output
    # varies over the manual axes hidden/label vary over, and lax.scan
    # requires carry-in and carry-out types to match — a fresh
    # replicated constant would not.  The where (not hidden*0, which
    # is NaN for an inf/NaN element and would contaminate EVERY row's
    # loss) is exactly 0 for any input while still inheriting the
    # varying type; int label*0 is always 0.
    tie = (jnp.where(jnp.isfinite(hidden[0, 0]), 0.0, 0.0)
           + lbl[0] * 0).astype(jnp.float32)
    init = (jnp.full((n,), -jnp.inf, jnp.float32) + tie,
            jnp.zeros((n,), jnp.float32) + tie,
            jnp.zeros((n,), jnp.float32) + tie)
    idxs = jnp.arange(n_chunks, dtype=jnp.int32)
    xs = (w, bslabs, idxs) if has_bias else (w, idxs)
    (m, s, lab), _ = jax.lax.scan(slab, init, xs)
    if axis_name is not None:
        # Megatron assembly across the vocab shards: rescale each
        # rank's online stats to the global max, then ONE fused psum
        # carries both the normalizer partials and the label logits
        # (matching vocab_parallel_softmax_ce's collective budget).
        # pmax has no differentiation rule; stop_gradient is exact
        # here — the shift cancels analytically, so the loss gradient
        # flows entirely through s and lab
        m_g = lax.pmax(lax.stop_gradient(m), axis_name)
        s, lab = lax.psum(
            jnp.stack([s * jnp.exp(m - m_g), lab]), axis_name)
        m = m_g
    return m + jnp.log(s) - lab


# ---------------------------------------------------------------------------
# convolution — reference convolution.cc / deconvolution.cc
# ---------------------------------------------------------------------------


def _conv_dims(nd_spatial: int):
    if nd_spatial == 1:
        return ("NCH", "OIH", "NCH")
    if nd_spatial == 2:
        return ("NCHW", "OIHW", "NCHW")
    return ("NCDHW", "OIDHW", "NCDHW")


@register("Convolution", num_inputs=None)
def convolution(data, weight, *rest, kernel=(), stride=(), dilate=(),
                pad=(), num_filter=0, num_group=1, no_bias=False,
                layout=None, workspace=0, cudnn_tune=None,
                cudnn_off=False):
    k = len(kernel)
    stride = tuple(stride) if stride else (1,) * k
    dilate = tuple(dilate) if dilate else (1,) * k
    pad = tuple(pad) if pad else (0,) * k
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _conv_dims(k))
    # int8×int8 convs accumulate in int32 (MXU-native quantized path;
    # reference quantized_conv) — shared rule with dot/batch_dot
    pref = _int8_acc(data, weight)
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=pref)
    if not no_bias:
        bias = rest[0]
        out = out + jnp.reshape(bias, (1, -1) + (1,) * k)
    return out


@register("Deconvolution", num_inputs=None)
def deconvolution(data, weight, *rest, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), num_filter=0, num_group=1, no_bias=True,
                  layout=None, target_shape=(), workspace=0,
                  cudnn_tune=None, cudnn_off=False):
    """Transposed conv == gradient of the forward conv w.r.t. its input
    (the reference's deconvolution-inl.h definition), so it is computed
    as exactly that: the vjp of ``conv_general_dilated`` whose weight is
    the MXNet deconv layout (C_in, num_filter/num_group, *kernel).
    This stays correct across groups/dilation/adj, where hand-translated
    conv_transpose padding arithmetic diverges."""
    import jax as _jax
    k = len(kernel)
    stride = tuple(stride) if stride else (1,) * k
    pad = tuple(pad) if pad else (0,) * k
    dilate = tuple(dilate) if dilate else (1,) * k
    adj = tuple(adj) if adj else (0,) * k
    for i in range(k):
        if adj[i] >= stride[i]:
            raise ValueError(
                f"Deconvolution: adj[{i}]={adj[i]} must be < "
                f"stride[{i}]={stride[i]}")
    n_filter = num_filter or weight.shape[1] * num_group
    if target_shape:
        # reference semantics (deconvolution-inl.h InferPad, bCal
        # branch): target_shape OVERRIDES both pad and adj — padding is
        # inferred as pad=(total+1)/2 with adj=total%2 adding back one
        # element at the end, i.e. an effective asymmetric crop of
        # (ceil(total/2), floor(total/2)) with the odd remainder
        # absorbed on the LOW side
        out_sp = tuple(int(t) for t in target_shape)
        pad_pairs = []
        for i in range(k):
            total = ((data.shape[2 + i] - 1) * stride[i]
                     + (kernel[i] - 1) * dilate[i] + 1 - out_sp[i])
            if total < 0:
                raise ValueError(
                    f"Deconvolution: target_shape {target_shape} "
                    f"unreachable with kernel/stride/dilate along axis "
                    f"{i} (needs total pad {total})")
            pad_pairs.append(((total + 1) // 2, total // 2))
    else:
        pad_pairs = [(p, p) for p in pad]
        out_sp = tuple(
            (data.shape[2 + i] - 1) * stride[i] - 2 * pad[i]
            + (kernel[i] - 1) * dilate[i] + 1 + adj[i]
            for i in range(k))
    y_shape = (data.shape[0], n_filter) + out_sp
    dn = lax.conv_dimension_numbers(y_shape, weight.shape, _conv_dims(k))

    def fwd(y):
        return lax.conv_general_dilated(
            y, weight, window_strides=stride,
            padding=pad_pairs, rhs_dilation=dilate,
            dimension_numbers=dn, feature_group_count=num_group)

    _, vjp = _jax.vjp(fwd, jnp.zeros(y_shape, data.dtype))
    out = vjp(data)[0]
    if not no_bias and rest:
        out = out + jnp.reshape(rest[0], (1, -1) + (1,) * k)
    return out


# ---------------------------------------------------------------------------
# pooling — reference pooling.cc
# ---------------------------------------------------------------------------


@register("Pooling")
def pooling(data, *, kernel=(), pool_type="max", global_pool=False,
            stride=(), pad=(), pooling_convention="valid",
            count_include_pad=True, cudnn_off=False, layout=None):
    nd_sp = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        return jnp.mean(data, axis=axes, keepdims=True)
    k = tuple(kernel)
    stride = tuple(stride) if stride else (1,) * nd_sp
    pad = tuple(pad) if pad else (0,) * nd_sp
    window = (1, 1) + k
    strides = (1, 1) + stride
    sp_pads = [(p, p) for p in pad]
    if pooling_convention == "full":
        # ceil-based output size: widen right padding so the last window fits
        for i in range(nd_sp):
            x = data.shape[2 + i]
            out_full = -(-(x + 2 * pad[i] - k[i]) // stride[i]) + 1
            need = (out_full - 1) * stride[i] + k[i] - (x + 2 * pad[i])
            if need > 0:
                lo, hi = sp_pads[i]
                sp_pads[i] = (lo, hi + need)
    elif pooling_convention == "same":
        for i in range(nd_sp):
            x = data.shape[2 + i]
            out_same = -(-x // stride[i])
            need = max((out_same - 1) * stride[i] + k[i] - x, 0)
            sp_pads[i] = (need // 2, need - need // 2)
    pads = ((0, 0), (0, 0)) + tuple(sp_pads)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(data, 0.0, lax.add, window, strides, pads)
        if pool_type == "sum":
            return s
        if count_include_pad:
            return s / float(np.prod(k))
        ones = jnp.ones_like(data)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return s / cnt
    if pool_type == "lp":
        s = lax.reduce_window(jnp.abs(data) ** 2, 0.0, lax.add, window,
                              strides, pads)
        return jnp.sqrt(s)
    raise ValueError(f"unknown pool_type {pool_type}")


# ---------------------------------------------------------------------------
# normalization — reference batch_norm.cc, layer_norm.cc, l2_normalization.cc
# ---------------------------------------------------------------------------


@register("BatchNorm", num_inputs=5, num_outputs=3)
def batch_norm(data, gamma, beta, moving_mean, moving_var, *, eps=1e-5,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False,
               training=False):
    """Returns (out, batch_mean, batch_var).

    Aux-state (moving mean/var) mutation is done by the caller (gluon layer /
    nd wrapper) exactly like the reference's aux-array update; the op itself
    stays pure.  `training` is threaded in by the frontend from
    autograd.is_training().
    """
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    reduce_axes = tuple(i for i in range(data.ndim) if i != axis % data.ndim)
    bshape = [1] * data.ndim
    bshape[axis % data.ndim] = data.shape[axis % data.ndim]

    if training and not use_global_stats:
        mean = jnp.mean(data, axis=reduce_axes)
        var = jnp.var(data, axis=reduce_axes)
    else:
        mean, var = moving_mean, moving_var
    out = (data - mean.reshape(bshape)) * lax.rsqrt(
        var.reshape(bshape) + eps) * g.reshape(bshape) + beta.reshape(bshape)
    return out, mean, var


@register("LayerNorm", num_inputs=3)
def layer_norm(data, gamma, beta, *, axis=-1, eps=1e-5, output_mean_var=False):
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    bshape = [1] * data.ndim
    bshape[axis % data.ndim] = data.shape[axis % data.ndim]
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("RMSNorm", num_inputs=2)
def rms_norm(data, gamma, *, axis=-1, eps=1e-6):
    """TPU-era extension (no reference ancestor; needed for Llama-family)."""
    ms = jnp.mean(jnp.square(data), axis=axis, keepdims=True)
    return data * lax.rsqrt(ms + eps) * gamma


@register("InstanceNorm", num_inputs=3)
def instance_norm(data, gamma, beta, *, eps=1e-3):
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("L2Normalization")
def l2_normalization(data, *, eps=1e-10, mode="instance"):
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, data.ndim))
    nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / nrm


# ---------------------------------------------------------------------------
# dropout — reference dropout.cc; RNG key threaded by the frontend
# ---------------------------------------------------------------------------


@register("Dropout", num_inputs=2)
def dropout(data, key, *, p=0.5, mode="training", axes=(), training=False):
    if not training or p <= 0.0:
        return data
    shape = data.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(shape))
    keep = jax.random.bernoulli(
        jax.random.wrap_key_data(key), 1.0 - p, shape)
    return jnp.where(keep, data / (1.0 - p), jnp.zeros((), data.dtype))


# ---------------------------------------------------------------------------
# embedding-adjacent / misc nn
# ---------------------------------------------------------------------------


@register("UpSampling", num_inputs=None)
def upsampling(data, *rest, scale=1, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", workspace=0):
    """Reference src/operator/nn/upsampling.cc.  ``nearest`` repeats
    pixels; ``bilinear`` resizes with the standard align-corners=False
    linear kernel — equivalent to the reference's fixed-bilinear-weight
    deconvolution (callers there pass the conventional
    ``init.Bilinear()`` weight; a learnable variant is a Conv2DTranspose
    in user code, so the extra weight input, when given, is ignored)."""
    if sample_type == "nearest":
        return jnp.repeat(jnp.repeat(data, scale, axis=2),
                          scale, axis=3)
    if sample_type != "bilinear":
        raise NotImplementedError(
            f"UpSampling sample_type {sample_type!r}: only 'nearest' "
            "and 'bilinear' exist (reference upsampling.cc)")
    if rest:
        import warnings
        warnings.warn(
            "UpSampling(bilinear): the weight input is ignored — this "
            "op implements the FIXED bilinear kernel (init.Bilinear); "
            "for a learned upsampling filter use Conv2DTranspose")
    n, c, h, w = data.shape
    return jax.image.resize(data, (n, c, h * scale, w * scale),
                            method="linear")


@register("BilinearResize2D")
def bilinear_resize_2d(data, *, height=0, width=0, scale_height=None,
                       scale_width=None, mode="size"):
    n, c, h, w = data.shape
    th = height if height else int(h * scale_height)
    tw = width if width else int(w * scale_width)
    return jax.image.resize(data, (n, c, th, tw), method="linear")


@register("RNN", num_inputs=None, num_outputs=-1)
def rnn_fused(data, params, state, *rest, state_size=0, num_layers=1,
              mode="lstm", bidirectional=False, p=0.0, state_outputs=False,
              projection_size=None, use_sequence_length=False,
              lstm_state_clip_min=None, lstm_state_clip_max=None,
              lstm_state_clip_nan=False):
    """Fused multi-layer RNN (reference src/operator/rnn.cc).

    Implemented as lax.scan over time with per-layer cells; weights arrive
    packed in `params` using the reference's packed layout.  See
    mxnet_tpu/gluon/rnn for the layer that packs/unpacks.
    """
    raise NotImplementedError("fused RNN op is provided via gluon.rnn "
                              "layers (scan-based); direct nd.RNN lands "
                              "with the RNN milestone")


@register("BlockGrad")
def block_grad(data):
    return lax.stop_gradient(data)


alias("stop_gradient", "BlockGrad")


@register("MakeLoss")
def make_loss(data, *, grad_scale=1.0, valid_thresh=0.0,
              normalization="null"):
    return data


@register("identity")
def identity(data):
    return data


@register("amp_cast")
def amp_cast(data, *, dtype="float16"):
    return data.astype(dtype)


@register("amp_multicast", num_inputs=None, num_outputs=-1)
def amp_multicast(*data, num_outputs=1, cast_narrow=False):
    dtypes = [d.dtype for d in data]
    widest = jnp.result_type(*dtypes) if not cast_narrow else \
        sorted(dtypes, key=lambda d: jnp.dtype(d).itemsize)[0]
    return tuple(d.astype(widest) for d in data)


@register("all_finite", num_inputs=None)
def all_finite(*arrays, init_output=True):
    ok = jnp.array(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a)))
    return ok.astype("float32")


alias("multi_all_finite", "all_finite")


# ---------------------------------------------------------------------------
# round-2 gap closure: remaining reference NN ops
# (reference src/operator/nn/{group_norm,lrn}.cc,
#  src/operator/{spatial_transformer,grid_generator,bilinear_sampler,
#  correlation,crop}.cc)
# ---------------------------------------------------------------------------


@register("GroupNorm", num_inputs=3)
def group_norm(data, gamma, beta, *, num_groups=1, eps=1e-5,
               output_mean_var=False):
    """(N, C, ...) normalized per sample over channel groups;
    gamma/beta are PER GROUP, shape (num_groups,) — the reference
    group_norm.cc parameter layout."""
    n, c = data.shape[0], data.shape[1]
    spatial = data.shape[2:]
    x = data.reshape((n, num_groups, c // num_groups) + spatial)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    norm = (x - mean) * lax.rsqrt(var + eps)
    gshape = (1, num_groups) + (1,) * (x.ndim - 2)
    out = norm * gamma.reshape(gshape) + beta.reshape(gshape)
    return out.reshape(data.shape)


@register("LRN")
def lrn(data, *, nsize=5, alpha=1e-4, beta=0.75, knorm=2.0):
    """Local response normalization across channels (lrn.cc):
    out = x / (knorm + alpha/nsize * sum_window(x^2))^beta."""
    sq = jnp.square(data)
    half = nsize // 2
    pads = ((0, 0), (half, half)) + ((0, 0),) * (data.ndim - 2)
    window = (1, nsize) + (1,) * (data.ndim - 2)
    ssum = lax.reduce_window(sq, 0.0, lax.add, window,
                             (1,) * data.ndim, pads)
    return data / jnp.power(knorm + alpha / nsize * ssum, beta)


@register("GridGenerator")
def grid_generator(data, *, transform_type="affine", target_shape=(0, 0)):
    """Affine: data (N, 6) θ → sampling grid (N, 2, H, W) in [-1, 1]
    (x then y rows, the reference layout).  Warp: data IS the grid of
    offsets added to the identity grid."""
    h, w = int(target_shape[0]), int(target_shape[1])
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    if transform_type == "affine":
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, HW)
        theta = data.reshape(-1, 2, 3)
        grid = jnp.einsum("nij,jk->nik", theta, base)            # (N,2,HW)
        return grid.reshape(-1, 2, h, w)
    # warp: data (N, 2, H, W) PIXEL flow added to the identity grid of
    # the flow's own spatial shape, scaled into normalized units
    fh, fw = data.shape[2], data.shape[3]
    ys = jnp.linspace(-1.0, 1.0, fh)
    xs = jnp.linspace(-1.0, 1.0, fw)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ident = jnp.stack([gx, gy], axis=0)[None].astype(data.dtype)
    scale = jnp.asarray(
        [2.0 / max(fw - 1, 1), 2.0 / max(fh - 1, 1)],
        data.dtype).reshape(1, 2, 1, 1)
    return ident + data * scale


def _bilinear_sample_one(img, grid):
    """img (C, H, W); grid (2, Ho, Wo) in [-1, 1] → (C, Ho, Wo)."""
    c, h, w = img.shape
    gx = (grid[0] + 1.0) * (w - 1) / 2.0
    gy = (grid[1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def at(yi, xi):
        inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        vals = img[:, yc, xc]          # (C, Ho, Wo)
        return jnp.where(inb[None], vals, 0.0)

    out = (at(y0, x0) * (1 - wx) * (1 - wy)
           + at(y0, x0 + 1) * wx * (1 - wy)
           + at(y0 + 1, x0) * (1 - wx) * wy
           + at(y0 + 1, x0 + 1) * wx * wy)
    return out.astype(img.dtype)


@register("BilinearSampler", num_inputs=2)
def bilinear_sampler(data, grid):
    """data (N, C, H, W) sampled at grid (N, 2, Ho, Wo) ∈ [-1, 1]
    (bilinear_sampler.cc; zero padding outside)."""
    return jax.vmap(_bilinear_sample_one)(data, grid)


@register("SpatialTransformer", num_inputs=2)
def spatial_transformer(data, loc, *, target_shape=(0, 0),
                        transform_type="affine",
                        sampler_type="bilinear", cudnn_off=False):
    """Affine spatial transformer network head (spatial_transformer.cc)
    = GridGenerator(affine) + BilinearSampler."""
    grid = grid_generator(loc, transform_type=transform_type,
                          target_shape=target_shape)
    return bilinear_sampler(data, grid.astype(data.dtype))


@register("Correlation", num_inputs=2, num_outputs=1)
def correlation(data1, data2, *, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True):
    """FlowNet-style correlation (correlation.cc): per displacement
    (dy, dx), mean over the patch of data1·shifted(data2).

    Static displacement set → one fused XLA program; kernel_size>1 is
    realized with an average pool over the product map.
    """
    if stride1 != 1:
        raise NotImplementedError("Correlation: stride1 != 1")
    d = max_displacement
    p = pad_size
    radius = kernel_size // 2
    x1 = jnp.pad(data1, ((0, 0), (0, 0), (p, p), (p, p)))
    # zero-extend data2 by the displacement range so shifted reads see
    # ZEROS outside the (padded) image, matching the reference — a
    # plain roll would wrap values around the border
    x2 = jnp.pad(data2, ((0, 0), (0, 0), (p + d, p + d), (p + d, p + d)))
    n, c, h, w = x1.shape
    outs = []
    disps = range(-d, d + 1, stride2)
    for dy in disps:
        for dx in disps:
            sh = x2[:, :, d + dy:d + dy + h, d + dx:d + dx + w]
            # is_multiply=False is the SAD variant: positive sum of
            # absolute differences (correlation.cc semantics)
            prod = (x1 * sh) if is_multiply else jnp.abs(x1 - sh)
            m = jnp.mean(prod, axis=1)           # (N, H, W), mean over C
            if kernel_size > 1:
                k = kernel_size
                m = lax.reduce_window(
                    m, 0.0, lax.add, (1, k, k), (1, 1, 1),
                    ((0, 0), (radius, radius),
                     (radius, radius))) / float(k * k)
            outs.append(m)
    out = jnp.stack(outs, axis=1)
    # reference output crops the border where windows fall off the
    # padded extent: H_out = H + 2p - 2*(d + kernel_radius)
    border = d + radius
    if border:
        out = out[:, :, border:h - border, border:w - border]
    return out


@register("Crop", num_inputs=None)
def crop(data, *rest, offset=(0, 0), h_w=(0, 0), num_args=1,
         center_crop=False):
    """Crop data to h_w (or to the 2nd input's spatial size) at offset
    (crop.cc)."""
    if len(rest) >= 1 and num_args == 2:
        th, tw = rest[0].shape[2], rest[0].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    h, w = data.shape[2], data.shape[3]
    if center_crop:
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    return data[:, :, oy:oy + th, ox:ox + tw]


def _deform_bilinear(data_g, y, x):
    """data_g (B, dg, Cg, H, W) sampled at absolute pixel coords
    y/x (B, dg, K, Ho, Wo) with zero padding outside → patches
    (B, dg, Cg, K, Ho, Wo)."""
    b, dg, cg, h, w = data_g.shape

    def corner(yi, xi):
        inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        flat = data_g.reshape(b, dg, cg, h * w)
        idx = (yc * w + xc).reshape(b, dg, 1, -1)
        idx = jnp.broadcast_to(idx, (b, dg, cg, idx.shape[-1]))
        vals = jnp.take_along_axis(flat, idx, axis=-1)
        vals = vals.reshape((b, dg, cg) + yi.shape[2:])
        return jnp.where(inb[:, :, None], vals, 0.0)

    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy = (y - y0)[:, :, None]
    wx = (x - x0)[:, :, None]
    return (corner(y0, x0) * (1 - wy) * (1 - wx)
            + corner(y0, x0 + 1) * (1 - wy) * wx
            + corner(y0 + 1, x0) * wy * (1 - wx)
            + corner(y0 + 1, x0 + 1) * wy * wx)


def _deform_conv_impl(data, offset, weight, rest, mask, kernel,
                      stride, dilate, pad, num_group,
                      num_deformable_group, no_bias):
    """Shared v1/v2 deformable-conv body: build the sampled patches
    tensor with vectorized corner gathers (optionally modulated by a
    per-tap mask) and reduce via one grouped einsum."""
    kh, kw = kernel
    sh, sw = tuple(stride) if stride else (1, 1)
    dh, dw = tuple(dilate) if dilate else (1, 1)
    ph, pw = tuple(pad) if pad else (0, 0)
    b, c, h, w = data.shape
    dg = num_deformable_group
    K = kh * kw
    ho = (h + 2 * ph - ((kh - 1) * dh + 1)) // sh + 1
    wo = (w + 2 * pw - ((kw - 1) * dw + 1)) // sw + 1

    ys = jnp.arange(ho) * sh - ph
    xs = jnp.arange(wo) * sw - pw
    ry = jnp.repeat(jnp.arange(kh) * dh, kw)
    rx = jnp.tile(jnp.arange(kw) * dw, kh)
    base_y = ry[:, None, None] + ys[None, :, None]
    base_x = rx[:, None, None] + xs[None, None, :]

    off = offset.reshape(b, dg, K, 2, ho, wo)
    y = base_y[None, None] + off[:, :, :, 0]
    x = base_x[None, None] + off[:, :, :, 1]

    data_g = data.reshape(b, dg, c // dg, h, w)
    patches = _deform_bilinear(data_g.astype(jnp.float32),
                               y.astype(jnp.float32),
                               x.astype(jnp.float32))
    if mask is not None:
        mod = mask.reshape(b, dg, 1, K, ho, wo).astype(jnp.float32)
        patches = patches * mod
    patches = patches.reshape(b, c, K, ho, wo).astype(data.dtype)

    ng = num_group
    o = weight.shape[0]
    wt = weight.reshape(ng, o // ng, c // ng, K)
    pg = patches.reshape(b, ng, c // ng, K, ho, wo)
    out = jnp.einsum("bgckhw,gock->bgohw", pg, wt,
                     preferred_element_type=jnp.float32)
    out = out.reshape(b, o, ho, wo).astype(data.dtype)
    if not no_bias:
        out = out + jnp.reshape(rest[0], (1, -1, 1, 1))
    return out


@register("_contrib_DeformableConvolution", num_inputs=None)
def deformable_convolution(data, offset, weight, *rest, kernel=(),
                           stride=(), dilate=(), pad=(), num_filter=0,
                           num_group=1, num_deformable_group=1,
                           no_bias=False, workspace=0, layout=None):
    """Deformable convolution v1 (reference:
    ``src/operator/contrib/deformable_convolution.cc``): each kernel
    tap samples the input at its base position plus a LEARNED offset,
    bilinearly interpolated with zero padding outside.

    TPU-first shape: instead of the reference's deformable-im2col CUDA
    kernel, the sampled patches tensor (B, C, K, Ho, Wo) is built with
    vectorized corner gathers and the conv reduces via one einsum over
    (C, K) — a dense MXU matmul.  offset layout matches the reference:
    (B, 2*dg*kh*kw, Ho, Wo), pairs ordered (y, x) per tap, taps
    row-major, per deformable group.
    """
    return _deform_conv_impl(data, offset, weight, rest, None, kernel,
                             stride, dilate, pad, num_group,
                             num_deformable_group, no_bias)


@register("_contrib_ModulatedDeformableConvolution", num_inputs=None)
def modulated_deformable_convolution(data, offset, mask, weight, *rest,
                                     kernel=(), stride=(), dilate=(),
                                     pad=(), num_filter=0, num_group=1,
                                     num_deformable_group=1,
                                     no_bias=False, workspace=0,
                                     layout=None):
    """Deformable convolution v2 (reference:
    ``src/operator/contrib/modulated_deformable_convolution.cc``):
    v1's learned offsets plus a per-tap modulation MASK (the mask
    input is already post-sigmoid in the reference op) scaling every
    sampled value.  mask: (B, dg*kh*kw, Ho, Wo); everything else
    matches ``_contrib_DeformableConvolution`` (shared body)."""
    return _deform_conv_impl(data, offset, weight, rest, mask, kernel,
                             stride, dilate, pad, num_group,
                             num_deformable_group, no_bias)
