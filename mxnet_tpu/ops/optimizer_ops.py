"""Optimizer update operators.

Capability parity: reference ``src/operator/optimizer_op*`` (SGD/momentum,
NAG, Adam, RMSProp, FTRL, Signum, LAMB, multi-precision ``mp_*`` variants,
fused multi-tensor updates) — SURVEY.md §2.2.  As in the reference, the
optimizer math executes as device-side ops — the Python optimizer classes
only pick ops and schedule hyper-parameters.  Learning rate and weight decay
ride as dynamic 0-d arrays (no recompilation when a scheduler changes them).

All ops are pure: they RETURN the updated tensors; the frontend writes them
back via ``out=`` (buffer swap), which is the TPU-native equivalent of the
reference's in-place kernels.

``rescale_grad`` rides as a DYNAMIC scalar everywhere (scalar_attrs), not
a static attr: ``Trainer.step`` rewrites it to ``scale/batch_size`` every
call, so a float in the jit-cache key would retrace per distinct batch
size (the classic cache-key blowup mxlint MXL401 flags).

The ``multi_*`` family mirrors the reference's fused multi-tensor kernels
(``src/operator/optimizer_op.cc``): flat lists of (weight, grad, state…)
in, ALL updated tensors out of ONE traced program, with per-param lr/wd
stacked into 1-d dynamic arrays.  ``clip_global_norm`` (off at -1) folds
global-norm gradient clipping into the same program — it needs every
gradient in one trace, which the per-param ops cannot express.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


def _prep_grad(grad, rescale_grad, clip_gradient, wd=None, weight=None):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if wd is not None:
        g = g + wd * weight
    return g


def _row_mask(grad):
    """Lazy-update row mask: True for rows the (row-sparse) gradient
    touches.  Reference lazy semantics (sgd/adam with row_sparse grads)
    skip untouched rows entirely — no wd decay, no momentum/moment
    decay; here "touched" = any nonzero in the row."""
    axes = tuple(range(1, grad.ndim))
    m = jnp.any(grad != 0, axis=axes)
    return m.reshape(m.shape + (1,) * (grad.ndim - 1))


@register("sgd_update", num_inputs=2,
          scalar_attrs=("lr", "wd", "rescale_grad"))
def sgd_update(weight, grad, lr, wd, rescale_grad=1.0, *,
               clip_gradient=-1.0, lazy_update=False):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_w = weight - lr * g
    if lazy_update:
        return jnp.where(_row_mask(grad), new_w, weight)
    return new_w


@register("sgd_mom_update", num_inputs=3,
          scalar_attrs=("lr", "wd", "rescale_grad"), num_outputs=2)
def sgd_mom_update(weight, grad, mom, lr, wd, rescale_grad=1.0, *,
                   momentum=0.0, clip_gradient=-1.0,
                   lazy_update=False):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - lr * g
    if lazy_update:
        mask = _row_mask(grad)
        new_mom = jnp.where(mask, new_mom, mom)
        return jnp.where(mask, weight + new_mom, weight), new_mom
    return weight + new_mom, new_mom


@register("nag_mom_update", num_inputs=3,
          scalar_attrs=("lr", "wd", "rescale_grad"), num_outputs=2)
def nag_mom_update(weight, grad, mom, lr, wd, rescale_grad=1.0, *,
                   momentum=0.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


# mp ops anchor their scalars on the float32 master weight (not the
# fp16 input) so lr/wd/rescale keep full precision in the update math
@register("mp_sgd_update", num_inputs=3,
          scalar_attrs=("lr", "wd", "rescale_grad"), num_outputs=2,
          scalar_ref_input=2)
def mp_sgd_update(weight, grad, weight32, lr, wd, rescale_grad=1.0, *,
                  clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad.astype("float32"), rescale_grad, clip_gradient,
                   wd, weight32)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", num_inputs=4,
          scalar_attrs=("lr", "wd", "rescale_grad"), num_outputs=3,
          scalar_ref_input=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr, wd,
                      rescale_grad=1.0, *, momentum=0.0,
                      clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad.astype("float32"), rescale_grad, clip_gradient,
                   wd, weight32)
    new_mom = momentum * mom - lr * g
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


@register("adam_update", num_inputs=4,
          scalar_attrs=("lr", "wd", "rescale_grad"), num_outputs=3)
def adam_update(weight, grad, mean, var, lr, wd, rescale_grad=1.0, *,
                beta1=0.9, beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                lazy_update=False):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    if lazy_update:
        mask = _row_mask(grad)
        return (jnp.where(mask, w, weight),
                jnp.where(mask, new_mean, mean),
                jnp.where(mask, new_var, var))
    return w, new_mean, new_var


@register("adamw_update", num_inputs=4,
          scalar_attrs=("lr", "eta", "wd", "rescale_grad"), num_outputs=3)
def adamw_update(weight, grad, mean, var, lr, eta, wd, rescale_grad=1.0,
                 *, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    w = weight - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon)
                        + wd * weight)
    return w, new_mean, new_var


@register("rmsprop_update", num_inputs=3,
          scalar_attrs=("lr", "wd", "rescale_grad"), num_outputs=2)
def rmsprop_update(weight, grad, n, lr, wd, rescale_grad=1.0, *,
                   gamma1=0.95, epsilon=1e-8, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = gamma1 * n + (1.0 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n


@register("rmspropalex_update", num_inputs=5,
          scalar_attrs=("lr", "wd", "rescale_grad"), num_outputs=4)
def rmspropalex_update(weight, grad, n, g_acc, delta, lr, wd,
                       rescale_grad=1.0, *, gamma1=0.95, gamma2=0.9,
                       epsilon=1e-8, clip_gradient=-1.0,
                       clip_weights=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = gamma1 * n + (1.0 - gamma1) * jnp.square(g)
    new_g = gamma1 * g_acc + (1.0 - gamma1) * g
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(
        new_n - jnp.square(new_g) + epsilon)
    return weight + new_delta, new_n, new_g, new_delta


@register("ftrl_update", num_inputs=4,
          scalar_attrs=("lr", "wd", "rescale_grad"), num_outputs=3)
def ftrl_update(weight, grad, z, n, lr, wd, rescale_grad=1.0, *,
                lamda1=0.01, beta=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(new_z) <= lamda1, jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return w, new_z, new_n


@register("signsgd_update", num_inputs=2,
          scalar_attrs=("lr", "wd", "rescale_grad"))
def signsgd_update(weight, grad, lr, wd, rescale_grad=1.0, *,
                   clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * jnp.sign(g)


@register("signum_update", num_inputs=3,
          scalar_attrs=("lr", "wd", "rescale_grad"), num_outputs=2)
def signum_update(weight, grad, mom, lr, wd, rescale_grad=1.0, *,
                  momentum=0.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - (1.0 - momentum) * g
    w = weight + lr * jnp.sign(new_mom)
    if wd_lh > 0:
        w = w - lr * wd_lh * weight
    return w, new_mom


@register("adagrad_update", num_inputs=3,
          scalar_attrs=("lr", "wd", "rescale_grad"), num_outputs=2)
def adagrad_update(weight, grad, history, lr, wd, rescale_grad=1.0, *,
                   epsilon=1e-7, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_h = history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(new_h) + epsilon), new_h


@register("adadelta_update", num_inputs=4,
          scalar_attrs=("wd", "rescale_grad"), num_outputs=3)
def adadelta_update(weight, grad, acc_g, acc_delta, wd, rescale_grad=1.0,
                    *, rho=0.9, epsilon=1e-5, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_acc_g = rho * acc_g + (1.0 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1.0 - rho) * jnp.square(delta)
    return weight - delta, new_acc_g, new_acc_delta


@register("lamb_update_phase1", num_inputs=4,
          scalar_attrs=("wd", "t", "rescale_grad"), num_outputs=3)
def lamb_update_phase1(weight, grad, mean, var, wd, t=1, rescale_grad=1.0,
                       *, beta1=0.9, beta2=0.999, epsilon=1e-6,
                       bias_correction=True, clip_gradient=-1.0):
    """``t`` (the step count for bias correction) rides as a DYNAMIC
    scalar so a training loop does not recompile phase1 every step."""
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    m, v = new_mean, new_var
    if bias_correction:
        tf = jnp.asarray(t, jnp.float32)
        m = m / (1.0 - jnp.power(jnp.float32(beta1), tf))
        v = v / (1.0 - jnp.power(jnp.float32(beta2), tf))
    update = m / (jnp.sqrt(v) + epsilon) + wd * weight
    return update, new_mean, new_var


@register("lamb_update_phase2", num_inputs=4, scalar_attrs=("lr",))
def lamb_update_phase2(weight, g_update, r1, r2, lr, *,
                       lower_bound=-1.0, upper_bound=-1.0):
    r1c = jnp.where(r1 == 0.0, jnp.ones_like(r1), r1)
    r2c = jnp.where(r2 == 0.0, jnp.ones_like(r2), r2)
    trust = jnp.where((r1 > 0.0) & (r2 > 0.0), r1c / r2c,
                      jnp.ones_like(r1))
    if lower_bound > 0:
        trust = jnp.maximum(trust, lower_bound)
    if upper_bound > 0:
        trust = jnp.minimum(trust, upper_bound)
    return weight - lr * trust * g_update


# ---------------------------------------------------------------------------
# fused multi-tensor updates (reference src/operator/optimizer_op.cc
# multi_sgd_update / multi_mp_sgd_mom_update / multi_sum_sq / multi_lars)
#
# Input convention, shared by the whole family: the flat ``*arrays`` list
# is ``num_weights`` weights, then ``num_weights`` grads, then any state
# groups (each ``num_weights`` long), then the dynamic per-param scalars
# ``lrs`` (1-d, len num_weights), ``wds`` (1-d), and the 0-d
# ``rescale_grad``.  lr/wd/rescale change every step (schedulers, Adam
# bias correction, Trainer batch-size folding) and therefore MUST be
# array inputs; only structural knobs (num_weights, momentum, betas,
# clip bounds) are static attrs.
# ---------------------------------------------------------------------------


def _sum_sq(a):
    return jnp.sum(jnp.square(a.astype(jnp.float32)))


def _global_norm_scale(arrays, max_norm):
    """(pre-clip global 2-norm, min(1, max_norm/(norm+1e-8))) over ALL
    arrays, accumulated in float32."""
    total = _sum_sq(arrays[0])
    for a in arrays[1:]:
        total = total + _sum_sq(a)
    norm = jnp.sqrt(total)
    scale = jnp.minimum(jnp.float32(1.0), max_norm / (norm + 1e-8))
    return norm, scale


def _rescaled_grads(gs, rescale_grad, clip_gradient, clip_global_norm):
    """grad * rescale, then OPTIONAL global-norm clip (one scale factor
    computed over ALL grads — expressible only because the whole update
    is one traced program), then optional per-element clip."""
    gs = [g * rescale_grad for g in gs]
    if clip_global_norm > 0:
        _, scale = _global_norm_scale(gs, jnp.float32(clip_global_norm))
        gs = [g * scale.astype(g.dtype) for g in gs]
    if clip_gradient is not None and clip_gradient > 0:
        gs = [jnp.clip(g, -clip_gradient, clip_gradient) for g in gs]
    return gs


@register("multi_sgd_update", num_inputs=None, num_outputs=-1)
def multi_sgd_update(*arrays, num_weights, clip_gradient=-1.0,
                     clip_global_norm=-1.0):
    """Inputs: n weights, n grads, lrs, wds, rescale_grad.
    Outputs: n updated weights."""
    n = num_weights
    ws, gs = arrays[:n], arrays[n:2 * n]
    lrs, wds, rescale_grad = arrays[2 * n], arrays[2 * n + 1], \
        arrays[2 * n + 2]
    gs = _rescaled_grads(gs, rescale_grad, clip_gradient,
                         clip_global_norm)
    return tuple(
        (w - lrs[j] * (gs[j] + wds[j] * w)).astype(w.dtype)
        for j, w in enumerate(ws))


@register("multi_sgd_mom_update", num_inputs=None, num_outputs=-1)
def multi_sgd_mom_update(*arrays, num_weights, momentum=0.0,
                         clip_gradient=-1.0, clip_global_norm=-1.0):
    """Inputs: n weights, n grads, n momenta, lrs, wds, rescale_grad.
    Outputs: n updated weights, then n updated momenta."""
    n = num_weights
    ws, gs, moms = arrays[:n], arrays[n:2 * n], arrays[2 * n:3 * n]
    lrs, wds, rescale_grad = arrays[3 * n], arrays[3 * n + 1], \
        arrays[3 * n + 2]
    gs = _rescaled_grads(gs, rescale_grad, clip_gradient,
                         clip_global_norm)
    new_ws, new_moms = [], []
    for j, w in enumerate(ws):
        new_mom = momentum * moms[j] - lrs[j] * (gs[j] + wds[j] * w)
        new_ws.append((w + new_mom).astype(w.dtype))
        new_moms.append(new_mom.astype(moms[j].dtype))
    return tuple(new_ws) + tuple(new_moms)


@register("multi_mp_sgd_update", num_inputs=None, num_outputs=-1)
def multi_mp_sgd_update(*arrays, num_weights, clip_gradient=-1.0,
                        clip_global_norm=-1.0):
    """Inputs: n fp16 weights, n grads, n fp32 master weights, lrs, wds,
    rescale_grad.  Outputs: n updated fp16 weights, n updated masters."""
    n = num_weights
    ws, gs, w32s = arrays[:n], arrays[n:2 * n], arrays[2 * n:3 * n]
    lrs, wds, rescale_grad = arrays[3 * n], arrays[3 * n + 1], \
        arrays[3 * n + 2]
    gs = _rescaled_grads([g.astype("float32") for g in gs], rescale_grad,
                         clip_gradient, clip_global_norm)
    new_ws, new_w32s = [], []
    for j, w32 in enumerate(w32s):
        nw32 = w32 - lrs[j] * (gs[j] + wds[j] * w32)
        new_ws.append(nw32.astype(ws[j].dtype))
        new_w32s.append(nw32)
    return tuple(new_ws) + tuple(new_w32s)


@register("multi_mp_sgd_mom_update", num_inputs=None, num_outputs=-1)
def multi_mp_sgd_mom_update(*arrays, num_weights, momentum=0.0,
                            clip_gradient=-1.0, clip_global_norm=-1.0):
    """Inputs: n fp16 weights, n grads, n fp32 momenta, n fp32 master
    weights, lrs, wds, rescale_grad.  Outputs: n updated fp16 weights,
    n momenta, n masters."""
    n = num_weights
    ws, gs = arrays[:n], arrays[n:2 * n]
    moms, w32s = arrays[2 * n:3 * n], arrays[3 * n:4 * n]
    lrs, wds, rescale_grad = arrays[4 * n], arrays[4 * n + 1], \
        arrays[4 * n + 2]
    gs = _rescaled_grads([g.astype("float32") for g in gs], rescale_grad,
                         clip_gradient, clip_global_norm)
    new_ws, new_moms, new_w32s = [], [], []
    for j, w32 in enumerate(w32s):
        new_mom = momentum * moms[j] - lrs[j] * (gs[j] + wds[j] * w32)
        nw32 = w32 + new_mom
        new_ws.append(nw32.astype(ws[j].dtype))
        new_moms.append(new_mom)
        new_w32s.append(nw32)
    return tuple(new_ws) + tuple(new_moms) + tuple(new_w32s)


@register("multi_adam_update", num_inputs=None, num_outputs=-1)
def multi_adam_update(*arrays, num_weights, beta1=0.9, beta2=0.999,
                      epsilon=1e-8, clip_gradient=-1.0,
                      clip_global_norm=-1.0):
    """Fused Adam over n tensors.  Inputs: n weights, n grads, n means,
    n vars, lrs (bias-corrected per param, computed host-side exactly as
    the per-param ``Adam.update`` does), wds, rescale_grad.  Outputs:
    n weights, n means, n vars."""
    n = num_weights
    ws, gs = arrays[:n], arrays[n:2 * n]
    means, variances = arrays[2 * n:3 * n], arrays[3 * n:4 * n]
    lrs, wds, rescale_grad = arrays[4 * n], arrays[4 * n + 1], \
        arrays[4 * n + 2]
    gs = _rescaled_grads(gs, rescale_grad, clip_gradient,
                         clip_global_norm)
    new_ws, new_means, new_vars = [], [], []
    for j, w in enumerate(ws):
        g = gs[j] + wds[j] * w
        new_mean = beta1 * means[j] + (1.0 - beta1) * g
        new_var = beta2 * variances[j] + (1.0 - beta2) * jnp.square(g)
        new_ws.append(
            (w - lrs[j] * new_mean / (jnp.sqrt(new_var) + epsilon))
            .astype(w.dtype))
        # state dtype preserved (f32 lr/wd would otherwise promote fp16
        # states, breaking donation aliasing and path equivalence)
        new_means.append(new_mean.astype(means[j].dtype))
        new_vars.append(new_var.astype(variances[j].dtype))
    return tuple(new_ws) + tuple(new_means) + tuple(new_vars)


@register("multi_lamb_update", num_inputs=None, num_outputs=-1)
def multi_lamb_update(*arrays, num_weights, beta1=0.9, beta2=0.999,
                      epsilon=1e-6, bias_correction=True,
                      lower_bound=-1.0, upper_bound=-1.0,
                      clip_gradient=-1.0, clip_global_norm=-1.0):
    """Fused LAMB (phase1 + per-tensor trust ratio + phase2 in one
    program).  Inputs: n weights, n grads, n means, n vars, lrs, wds,
    ts (per-param step counts, 1-d), rescale_grad.  Outputs: n weights,
    n means, n vars."""
    n = num_weights
    ws, gs = arrays[:n], arrays[n:2 * n]
    means, variances = arrays[2 * n:3 * n], arrays[3 * n:4 * n]
    lrs, wds, ts, rescale_grad = arrays[4 * n], arrays[4 * n + 1], \
        arrays[4 * n + 2], arrays[4 * n + 3]
    gs = _rescaled_grads(gs, rescale_grad, clip_gradient,
                         clip_global_norm)
    new_ws, new_means, new_vars = [], [], []
    for j, w in enumerate(ws):
        g = gs[j]
        new_mean = beta1 * means[j] + (1.0 - beta1) * g
        new_var = beta2 * variances[j] + (1.0 - beta2) * jnp.square(g)
        m, v = new_mean, new_var
        if bias_correction:
            tf = jnp.asarray(ts[j], jnp.float32)
            m = m / (1.0 - jnp.power(jnp.float32(beta1), tf))
            v = v / (1.0 - jnp.power(jnp.float32(beta2), tf))
        update = m / (jnp.sqrt(v) + epsilon) + wds[j] * w
        r1 = jnp.sqrt(jnp.sum(jnp.square(w)))
        r2 = jnp.sqrt(jnp.sum(jnp.square(update)))
        r1c = jnp.where(r1 == 0.0, jnp.ones_like(r1), r1)
        r2c = jnp.where(r2 == 0.0, jnp.ones_like(r2), r2)
        trust = jnp.where((r1 > 0.0) & (r2 > 0.0), r1c / r2c,
                          jnp.ones_like(r1))
        if lower_bound > 0:
            trust = jnp.maximum(trust, lower_bound)
        if upper_bound > 0:
            trust = jnp.minimum(trust, upper_bound)
        new_ws.append((w - lrs[j] * trust * update).astype(w.dtype))
        new_means.append(new_mean.astype(means[j].dtype))
        new_vars.append(new_var.astype(variances[j].dtype))
    return tuple(new_ws) + tuple(new_means) + tuple(new_vars)


@register("multi_sum_sq", num_inputs=None)
def multi_sum_sq(*arrays, num_arrays):
    """Per-array sum of squares, stacked into one 1-d float32 output
    (reference ``multi_sum_sq``; feeds ``multi_lars``)."""
    return jnp.stack([_sum_sq(a) for a in arrays[:num_arrays]])


@register("multi_lars", num_inputs=4, scalar_attrs=("rescale_grad",))
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, rescale_grad=1.0,
               *, eta=0.001, eps=1e-8):
    """LARS layer-wise lr scaling over the stacked norms from
    ``multi_sum_sq`` (reference ``multi_lars``): where both norms are
    positive, lr_j *= eta * ||w_j|| / (||g_j|| + wd_j * ||w_j|| + eps)."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    ratio = eta * w_norm / (g_norm + wds * w_norm + eps)
    return lrs * jnp.where((w_norm > 0.0) & (g_norm > 0.0), ratio,
                           jnp.ones_like(ratio))


@register("clip_by_global_norm", num_inputs=None, num_outputs=-1,
          scalar_attrs=("max_norm",), scalar_ref_input=None)
def clip_by_global_norm(*arrays):
    """Scale ALL arrays so their global 2-norm is <= max_norm; returns
    the scaled arrays followed by the (pre-clip) global norm.  One
    traced program — the gluon ``clip_global_norm`` util dispatches this
    once instead of ~3n per-array ops.

    ``max_norm`` rides as the trailing DYNAMIC scalar (variadic ops
    receive scalar_attrs appended to ``*arrays``): the Trainer fallback
    clips with a batch-size-dependent bound every step, which must not
    retrace.  ``scalar_ref_input=None`` stages it as float32 — anchoring
    on fp16 gradients would overflow any bound > 65504 to inf and
    silently skip the clip."""
    *arrs, max_norm = arrays
    norm, scale = _global_norm_scale(arrs, max_norm.astype(jnp.float32))
    return tuple((a * scale.astype(a.dtype)) for a in arrs) + (norm,)
