"""Optimizer update operators.

Capability parity: reference ``src/operator/optimizer_op*`` (SGD/momentum,
NAG, Adam, RMSProp, FTRL, Signum, LAMB, multi-precision ``mp_*`` variants,
fused multi-tensor updates) — SURVEY.md §2.2.  As in the reference, the
optimizer math executes as device-side ops — the Python optimizer classes
only pick ops and schedule hyper-parameters.  Learning rate and weight decay
ride as dynamic 0-d arrays (no recompilation when a scheduler changes them).

All ops are pure: they RETURN the updated tensors; the frontend writes them
back via ``out=`` (buffer swap), which is the TPU-native equivalent of the
reference's in-place kernels.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


def _prep_grad(grad, rescale_grad, clip_gradient, wd=None, weight=None):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if wd is not None:
        g = g + wd * weight
    return g


def _row_mask(grad):
    """Lazy-update row mask: True for rows the (row-sparse) gradient
    touches.  Reference lazy semantics (sgd/adam with row_sparse grads)
    skip untouched rows entirely — no wd decay, no momentum/moment
    decay; here "touched" = any nonzero in the row."""
    axes = tuple(range(1, grad.ndim))
    m = jnp.any(grad != 0, axis=axes)
    return m.reshape(m.shape + (1,) * (grad.ndim - 1))


@register("sgd_update", num_inputs=2, scalar_attrs=("lr", "wd"))
def sgd_update(weight, grad, lr, wd, *, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=False):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_w = weight - lr * g
    if lazy_update:
        return jnp.where(_row_mask(grad), new_w, weight)
    return new_w


@register("sgd_mom_update", num_inputs=3, scalar_attrs=("lr", "wd"),
          num_outputs=2)
def sgd_mom_update(weight, grad, mom, lr, wd, *, momentum=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0,
                   lazy_update=False):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - lr * g
    if lazy_update:
        mask = _row_mask(grad)
        new_mom = jnp.where(mask, new_mom, mom)
        return jnp.where(mask, weight + new_mom, weight), new_mom
    return weight + new_mom, new_mom


@register("nag_mom_update", num_inputs=3, scalar_attrs=("lr", "wd"),
          num_outputs=2)
def nag_mom_update(weight, grad, mom, lr, wd, *, momentum=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("mp_sgd_update", num_inputs=3, scalar_attrs=("lr", "wd"),
          num_outputs=2)
def mp_sgd_update(weight, grad, weight32, lr, wd, *, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad.astype("float32"), rescale_grad, clip_gradient,
                   wd, weight32)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", num_inputs=4, scalar_attrs=("lr", "wd"),
          num_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr, wd, *, momentum=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    g = _prep_grad(grad.astype("float32"), rescale_grad, clip_gradient,
                   wd, weight32)
    new_mom = momentum * mom - lr * g
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


@register("adam_update", num_inputs=4, scalar_attrs=("lr", "wd"),
          num_outputs=3)
def adam_update(weight, grad, mean, var, lr, wd, *, beta1=0.9, beta2=0.999,
                epsilon=1e-8, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=False):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    if lazy_update:
        mask = _row_mask(grad)
        return (jnp.where(mask, w, weight),
                jnp.where(mask, new_mean, mean),
                jnp.where(mask, new_var, var))
    return w, new_mean, new_var


@register("adamw_update", num_inputs=4,
          scalar_attrs=("lr", "eta", "wd"), num_outputs=3)
def adamw_update(weight, grad, mean, var, lr, eta, wd, *, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, rescale_grad=1.0,
                 clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    w = weight - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon)
                        + wd * weight)
    return w, new_mean, new_var


@register("rmsprop_update", num_inputs=3, scalar_attrs=("lr", "wd"),
          num_outputs=2)
def rmsprop_update(weight, grad, n, lr, wd, *, gamma1=0.95, epsilon=1e-8,
                   rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = gamma1 * n + (1.0 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n


@register("rmspropalex_update", num_inputs=5, scalar_attrs=("lr", "wd"),
          num_outputs=4)
def rmspropalex_update(weight, grad, n, g_acc, delta, lr, wd, *, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = gamma1 * n + (1.0 - gamma1) * jnp.square(g)
    new_g = gamma1 * g_acc + (1.0 - gamma1) * g
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(
        new_n - jnp.square(new_g) + epsilon)
    return weight + new_delta, new_n, new_g, new_delta


@register("ftrl_update", num_inputs=4, scalar_attrs=("lr", "wd"),
          num_outputs=3)
def ftrl_update(weight, grad, z, n, lr, wd, *, lamda1=0.01, beta=1.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(new_z) <= lamda1, jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return w, new_z, new_n


@register("signsgd_update", num_inputs=2, scalar_attrs=("lr", "wd"))
def signsgd_update(weight, grad, lr, wd, *, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * jnp.sign(g)


@register("signum_update", num_inputs=3, scalar_attrs=("lr", "wd"),
          num_outputs=2)
def signum_update(weight, grad, mom, lr, wd, *, momentum=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - (1.0 - momentum) * g
    w = weight + lr * jnp.sign(new_mom)
    if wd_lh > 0:
        w = w - lr * wd_lh * weight
    return w, new_mom


@register("adagrad_update", num_inputs=3, scalar_attrs=("lr", "wd"),
          num_outputs=2)
def adagrad_update(weight, grad, history, lr, wd, *, epsilon=1e-7,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_h = history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(new_h) + epsilon), new_h


@register("adadelta_update", num_inputs=4, scalar_attrs=("wd",),
          num_outputs=3)
def adadelta_update(weight, grad, acc_g, acc_delta, wd, *, rho=0.9,
                    epsilon=1e-5, rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_acc_g = rho * acc_g + (1.0 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1.0 - rho) * jnp.square(delta)
    return weight - delta, new_acc_g, new_acc_delta


@register("lamb_update_phase1", num_inputs=4,
          scalar_attrs=("wd", "t"), num_outputs=3)
def lamb_update_phase1(weight, grad, mean, var, wd, t=1, *, beta1=0.9,
                       beta2=0.999, epsilon=1e-6, bias_correction=True,
                       rescale_grad=1.0, clip_gradient=-1.0):
    """``t`` (the step count for bias correction) rides as a DYNAMIC
    scalar so a training loop does not recompile phase1 every step."""
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    m, v = new_mean, new_var
    if bias_correction:
        tf = jnp.asarray(t, jnp.float32)
        m = m / (1.0 - jnp.power(jnp.float32(beta1), tf))
        v = v / (1.0 - jnp.power(jnp.float32(beta2), tf))
    update = m / (jnp.sqrt(v) + epsilon) + wd * weight
    return update, new_mean, new_var


@register("lamb_update_phase2", num_inputs=4, scalar_attrs=("lr",))
def lamb_update_phase2(weight, g_update, r1, r2, lr, *,
                       lower_bound=-1.0, upper_bound=-1.0):
    r1c = jnp.where(r1 == 0.0, jnp.ones_like(r1), r1)
    r2c = jnp.where(r2 == 0.0, jnp.ones_like(r2), r2)
    trust = jnp.where((r1 > 0.0) & (r2 > 0.0), r1c / r2c,
                      jnp.ones_like(r1))
    if lower_bound > 0:
        trust = jnp.maximum(trust, lower_bound)
    if upper_bound > 0:
        trust = jnp.minimum(trust, upper_bound)
    return weight - lr * trust * g_update
