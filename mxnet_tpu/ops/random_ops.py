"""Random sampling operators.

Capability parity: reference ``src/operator/random/`` (sample_op uniform /
normal / gamma / exponential / poisson / negative_binomial / multinomial,
shuffle) + the counter-based parallel PRNG in
``include/mxnet/random_generator.h`` — SURVEY.md §2.2.

TPU-native design: JAX threefry keys ARE the counter-based parallel RNG the
reference hand-built.  Every sampling op takes an explicit key array as its
first input; the frontend (``mxnet_tpu.random``) owns a per-context key that
``mx.random.seed`` resets — reproducing the reference's per-device seeded
generators with pure functions underneath.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _k(key):
    return jax.random.wrap_key_data(key)


@register("_random_uniform", num_inputs=1, scalar_attrs=("low", "high"),
          scalar_ref_input=None)
def _random_uniform(key, low, high, *, shape=(), dtype="float32"):
    return jax.random.uniform(_k(key), shape, dtype=dtype,
                              minval=low, maxval=high)


@register("_random_normal", num_inputs=1, scalar_attrs=("loc", "scale"),
          scalar_ref_input=None)
def _random_normal(key, loc, scale, *, shape=(), dtype="float32"):
    return jax.random.normal(_k(key), shape, dtype=dtype) * scale + loc


@register("_random_gamma", num_inputs=1, scalar_attrs=("alpha", "beta"),
          scalar_ref_input=None)
def _random_gamma(key, alpha, beta, *, shape=(), dtype="float32"):
    return jax.random.gamma(_k(key), alpha, shape, dtype=dtype) * beta


@register("_random_exponential", num_inputs=1, scalar_attrs=("lam",), scalar_ref_input=None)
def _random_exponential(key, lam, *, shape=(), dtype="float32"):
    return jax.random.exponential(_k(key), shape, dtype=dtype) / lam


@register("_random_poisson", num_inputs=1, scalar_attrs=("lam",), scalar_ref_input=None)
def _random_poisson(key, lam, *, shape=(), dtype="float32"):
    return jax.random.poisson(_k(key), lam, shape).astype(dtype)


@register("_random_randint", num_inputs=1)
def _random_randint(key, *, low=0, high=1, shape=(), dtype="int32"):
    return jax.random.randint(_k(key), shape, low, high, dtype=dtype)


@register("_random_bernoulli", num_inputs=1, scalar_attrs=("prob",), scalar_ref_input=None)
def _random_bernoulli(key, prob, *, shape=(), dtype="float32"):
    return jax.random.bernoulli(_k(key), prob, shape).astype(dtype)


@register("_sample_multinomial", num_inputs=2)
def _sample_multinomial(key, data, *, shape=(), get_prob=False,
                        dtype="int32"):
    """Categorical sampling over the trailing axis of `data` (probs)."""
    logits = jnp.log(jnp.clip(data, 1e-30, None))
    sample_shape = tuple(shape) if shape else ()
    if data.ndim == 1:
        out = jax.random.categorical(_k(key), logits, shape=sample_shape)
    else:
        out = jax.random.categorical(_k(key), logits,
                                     shape=sample_shape + data.shape[:-1],
                                     axis=-1)
        if sample_shape:
            out = jnp.moveaxis(out, 0, -1)
    return out.astype(dtype)


@register("_shuffle", num_inputs=2)
def _shuffle(key, data):
    return jax.random.permutation(_k(key), data, axis=0)


@register("_sample_unique_zipfian", num_inputs=1)
def _sample_unique_zipfian(key, *, range_max=1, shape=()):
    # approximate: log-uniform sampling without dedup guarantee.
    # 'int64' canonicalizes to int32 without x64, which would wrap for
    # range_max > 2**31 — sample in float and clip BEFORE the int cast
    u = jax.random.uniform(_k(key), shape)
    vals = jnp.exp(u * jnp.log(float(range_max))) - 1.0
    vals = jnp.clip(vals, 0.0, float(range_max - 1))
    return vals.astype("int64")
