"""Mixture-of-experts FFN with expert parallelism.

Beyond-reference capability (the reference has no MoE — SURVEY.md §2.3
parallelism checklist lists expert parallel as absent upstream); built
because the rebuild's distributed story treats ep as a first-class mesh
axis alongside dp/tp/sp.

TPU-first design (GShard/Switch dense-dispatch formulation):
- routing/dispatch are einsums over a STATIC capacity — no dynamic
  shapes, so the whole layer jits and fuses;
- expert FFNs run as ONE batched (E, C, d)×(E, d, h) matmul — MXU-sized
  instead of a Python loop over experts;
- under a mesh-jitted step with expert weights sharded over an ``ep``
  axis (``parallel.moe_param_rule``), GSPMD inserts the all-to-alls —
  the canonical expert-parallel lowering on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


@register("_contrib_MoEFFN", num_inputs=6, num_outputs=2)
def moe_ffn(x, gate_w, w1, b1, w2, b2, *, num_experts=1, k=1,
            capacity_factor=1.25, activation="relu"):
    """Top-k routed expert FFN.

    x (T, d); gate_w (d, E); w1 (E, d, h); b1 (E, h); w2 (E, h, d);
    b2 (E, d).  Returns (out (T, d), aux_loss ()) — aux_loss is the
    Switch-Transformer load-balancing loss (mean fraction · mean
    router prob per expert, scaled by E).
    """
    t, d = x.shape
    e = num_experts
    if k > e:
        raise ValueError(
            f"MoEFFN: k={k} exceeds num_experts={e}; a further routing "
            "round would silently double-dispatch to expert 0")
    logits = x @ gate_w                         # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)

    capacity = int(np.ceil(k * t / e * capacity_factor))
    capacity = max(capacity, 1)

    # routing/bookkeeping run in int32/float32 REGARDLESS of x.dtype:
    # bf16 cannot count past 256, so slot positions would collide and
    # silently merge tokens under AMP
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    remaining = probs.astype(jnp.float32)
    fill = jnp.zeros((e,), jnp.int32)
    for _ in range(k):
        choice = remaining.argmax(axis=-1)      # (T,)
        onehot_i = jax.nn.one_hot(choice, e, dtype=jnp.int32)
        onehot = onehot_i.astype(jnp.float32)
        # position of each token within its chosen expert's buffer
        pos = (jnp.cumsum(onehot_i, axis=0) - 1) + fill[None, :]
        pos_tok = jnp.sum(pos * onehot_i, axis=-1)
        keep = pos_tok < capacity
        gate = jnp.sum(probs.astype(jnp.float32) * onehot,
                       axis=-1) * keep
        combine = combine + (gate[:, None, None]
                             * onehot[:, :, None]
                             * jax.nn.one_hot(pos_tok, capacity,
                                              dtype=jnp.float32)[:, None, :])
        fill = fill + jnp.sum(onehot_i * keep[:, None], axis=0)
        remaining = remaining * (1.0 - onehot)  # next-best expert
    combine = combine.astype(x.dtype)

    dispatch = (combine > 0).astype(x.dtype)    # (T, E, C)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
    h = jnp.einsum("ecd,edh->ech", expert_in, w1) + b1[:, None, :]
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
           "silu": jax.nn.silu}[activation]
    h = act(h)
    expert_out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
    out = jnp.einsum("tec,ecd->td", combine, expert_out)

    # load-balancing aux loss (Switch eq. 4)
    frac_tokens = jnp.mean(
        jax.nn.one_hot(logits.argmax(-1), e, dtype=x.dtype), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = jnp.sum(frac_tokens * frac_probs) * e
    return out, aux
