"""Attention operators.

Capability parity: reference ``src/operator/contrib/transformer*`` —
interleaved-matmul self-attention helpers used by GluonNLP-era BERT
(SURVEY.md §2.2 "Sequence/attention-adjacent ops", §5 "Long-context").
TPU-native design: ONE fused scaled-dot-product-attention op instead of
the reference's four interleaved-matmul micro-ops — XLA fuses the
softmax(QKᵀ)V chain onto the MXU; on TPU a Pallas flash-attention kernel
(ops/flash_attention.py) handles long sequences without materializing the
S×S score matrix.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register

# trace-time count of dot_product_attention dispatches that chose the
# Pallas flash kernel (see the increment site for why this is proof)
_FLASH_DISPATCHES = 0


def flash_dispatch_count() -> int:
    return _FLASH_DISPATCHES


def _causal_band(s_q, s_k, window):
    """Causal mask, optionally banded: query i keeps keys in
    (i+off-window, i+off] with off = s_k - s_q (sliding window)."""
    cm = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
    if window is not None:
        cm &= ~jnp.tril(jnp.ones((s_q, s_k), bool),
                        k=s_k - s_q - int(window))
    return cm


def _sdpa_xla(q, k, v, mask, scale, causal, window=None):
    """Reference XLA path: (B, S, H, D) layout.

    Grouped-query attention is native: when K/V carry fewer heads than
    Q, query heads are grouped per KV head in the einsum — no
    materialized K/V repeat."""
    # keep the score pipeline in the input dtype (the MXU dtype under
    # AMP) and run ONLY the softmax in f32: a strongly-typed f32 scale
    # scalar would otherwise promote logits — and every backward dot of
    # the attention — to f32 (found by benchmark/hlo_dtype_audit.py)
    scale = jnp.asarray(scale, q.dtype)
    h, kv = q.shape[2], k.shape[2]
    if kv != h:
        b, s_q, _, d = q.shape
        s_k = k.shape[1]
        g = h // kv
        qg = q.reshape(b, s_q, kv, g, d)
        logits = jnp.einsum("bqcgd,bkcd->bcgqk", qg, k) * scale
        neg = jnp.asarray(-1e30, logits.dtype)
        if causal:
            cm = _causal_band(s_q, s_k, window)
            logits = jnp.where(cm[None, None, None], logits, neg)
        if mask is not None:
            m = mask.astype(bool)
            if m.ndim == 2:       # legacy (S_q, S_k) broadcast form
                m = m[None, None]
            if m.shape[1] == 1:
                m = m[:, :, None]                    # (B,1,1,Sq,Sk)
            else:
                # keep the mask's own batch dim so (1, H, Sq, Sk)
                # masks still broadcast over the query batch
                m = m.reshape(m.shape[0], kv, g, m.shape[2],
                              m.shape[3])
            logits = jnp.where(m, logits, neg)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        out = jnp.einsum("bcgqk,bkcd->bqcgd", probs.astype(v.dtype), v)
        return out.reshape(b, s_q, h, d).astype(q.dtype)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    neg = jnp.asarray(-1e30, logits.dtype)
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        cm = _causal_band(s_q, s_k, window)
        logits = jnp.where(cm[None, None], logits, neg)
    if mask is not None:
        logits = jnp.where(mask.astype(bool), logits, neg)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype),
                      v).astype(q.dtype)


@register("dot_product_attention", num_inputs=None)
def dot_product_attention(query, key, value, *rest, num_heads=1,
                          scale=None, causal=False, use_mask=False,
                          flash=True, window=None):
    """Fused multi-head SDPA.

    Inputs are (batch, seq, num_heads, head_dim); optional boolean mask
    (batch, 1|num_heads, seq_q, seq_k) as a 4th input when use_mask.
    ``window`` applies a sliding-window band to the causal mask
    (Mistral-style; requires causal=True).  Returns (batch, seq,
    num_heads, head_dim).
    """
    mask = rest[0] if use_mask and rest else None
    # NOTE: flash=True is a REQUEST, not a guarantee — the measured
    # crossover policy (_flash_preferred) may still route mid-range
    # sequences to XLA SDPA when that path benched faster, unless the
    # estimated S×S score tensor would blow the HBM budget.  Set
    # MXTPU_FLASH_MODE=always to force the kernel (or =never for XLA);
    # MXTPU_FLASH_XLA_FROM/_UNTIL tune the crossover window.
    if window is not None:
        # validate HERE so the XLA fallback cannot silently produce
        # uniform-attention garbage (window=0 clears the whole causal
        # mask) while the flash path raises for the identical call
        from ..base import MXNetError
        if not causal:
            raise MXNetError("dot_product_attention: window= requires "
                             "causal=True (sliding window is a banded "
                             "causal mask)")
        if int(window) <= 0:
            raise MXNetError("dot_product_attention: window must be "
                             f"positive, got {window}")
        if int(window) >= key.shape[1]:
            # band wider than the keys = plain causal: clamp BEFORE the
            # path choice so the measured flash-vs-XLA policy still
            # applies (forcing flash here would pick the slower kernel
            # exactly in the XLA-wins range)
            window = None
    d = query.shape[-1]
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    from .flash_attention import _as_key_padding
    # _as_key_padding is the ONE decision point: unambiguous key-padding
    # masks go to the kernel; everything else (query-dependent 4-D,
    # ambiguous/broadcastable 2-D) keeps the XLA broadcast behavior
    kmask = _as_key_padding(mask, batch=query.shape[0],
                            s_k=key.shape[1], s_q=query.shape[1])
    if kmask is not None and mask.ndim == 2:
        # normalize the documented 2-D key-padding form for the XLA
        # path too (the shape RULE lives only in _as_key_padding)
        mask = mask.reshape(mask.shape[0], 1, 1, mask.shape[1])
    # a sliding window prefers the kernel: block-skip makes it O(S·W)
    # while the XLA path masks a full S×S band — measured r5 window
    # (bench_logs/r5/attention_bench.log): flash banded 3.9x faster at
    # seq 512/w256 and 6.6x at 1024/w256, par at 2048/w1024.  The one
    # contrary row (2048/w256, XLA 2.8x) contradicts the kernel's own
    # linear-in-seq scaling from the 1024/w256 row by ~4x and is
    # queued for re-measure before it may move this policy.
    preferred = (window is not None
                 or _flash_preferred(query.shape[1], key.shape[1],
                                     batch=query.shape[0],
                                     heads=query.shape[2],
                                     causal=causal))
    if flash and (mask is None or kmask is not None) \
            and _flash_viable(query, key) and preferred:
        # dispatch evidence: incremented at TRACE time, so a nonzero
        # count proves the compiled program contains the Pallas kernel
        # (bench asserts this instead of hoping — VERDICT r2 weak #2)
        global _FLASH_DISPATCHES
        _FLASH_DISPATCHES += 1
        from .flash_attention import flash_attention
        if key.shape[2] != query.shape[2]:
            # flash kernel wants equal heads: repeat K/V. The repeat
            # costs O(S·H·D) HBM but keeps attention O(S) instead of
            # the grouped XLA path's O(S²) score tensor — the right
            # trade on the long-context runs flash exists for.
            rep = query.shape[2] // key.shape[2]
            key = jnp.repeat(key, rep, axis=2)
            value = jnp.repeat(value, rep, axis=2)
        return flash_attention(query, key, value, kmask=kmask, scale=s,
                               causal=causal, window=window)
    return _sdpa_xla(query, key, value, mask, s, causal, window=window)


def _flash_preferred(s_q, s_k, batch=1, heads=1, causal=False):
    """Measured flash-vs-XLA crossover policy (VERDICT r3 #4: a hand
    kernel must win or step aside, the cuDNN-fast-path pattern).

    r5 on-chip evidence, v5e.  The standalone kernel-vs-XLA microbench
    (bench_logs/r5/attention_bench{,2}.log) showed a mixed, noisy,
    causality-dependent table — but the IN-MODEL A/B settled it:
    BERT-base b64 s128, identical math, same window, honest-slope —
    flash kernel 956.9 samples/sec vs XLA SDPA **1535.3** (MFU 0.53
    v1; bench_logs/r5/bench_xlaattn.log).  A Pallas custom-call is a
    fusion BARRIER: standalone timings miss that XLA fuses the qkv
    projections, scaling, residual and dropout INTO its attention
    when it owns the whole graph.  So inside XLA's comfortable regime
    the compiler wins, and the kernel's domain is what XLA cannot do:

      * sliding-window/banded attention (O(S·W) vs a masked S×S —
        measured 1.1-6.6x, handled by the caller before this policy);
      * score tensors beyond the HBM budget — batch·heads·s_q·s_k·4B
        over MXTPU_FLASH_XLA_MAX_SCORE_GB (default 2 GiB, ~1/8 of
        v5e HBM): flash, or the XLA path OOMs (ADVICE r4);
      * seq ≥ MXTPU_FLASH_XLA_UNTIL (default 4096): flash regardless
        (b4·h8·4096² f32 scores alone are 2.1 GiB).

    MXTPU_FLASH_XLA_FROM (causal) / MXTPU_FLASH_XLA_FROM_NONCAUSAL
    keep their "prefer flash below this seq" meaning for tuning but
    both now default to 0 — XLA everywhere the three rules above
    don't hand the kernel the job.  Update only from an IN-MODEL
    same-window A/B (microbench cells vary 2-3x run-to-run here).
    MXTPU_FLASH_MODE=always|never overrides (auto default).
    """
    from .. import envs
    mode = envs.get("MXTPU_FLASH_MODE").lower()
    if mode == "always":
        return True
    if mode == "never":
        return False
    s = max(s_q, s_k)
    # defaults live in the envs registry (ONE source of truth — the
    # generated docs/env_vars.md advertises exactly what runs here)
    xla_from = envs.get("MXTPU_FLASH_XLA_FROM" if causal
                        else "MXTPU_FLASH_XLA_FROM_NONCAUSAL")
    xla_until = envs.get("MXTPU_FLASH_XLA_UNTIL")
    if s < xla_from or s >= xla_until:
        return True
    score_gb = batch * heads * s_q * s_k * 4 / 2**30
    return score_gb > envs.get("MXTPU_FLASH_XLA_MAX_SCORE_GB")


def _flash_viable(q, k):
    """Pallas kernel needs TPU (or interpret mode) + 128-aligned seq
    lens; head_dim only needs 8-alignment — the kernel zero-pads it to
    the 128 lane width, so BERT's d=64 takes the flash path."""
    # through the typed registry so '0'/'false' parse as FALSE (the raw
    # environ read treated any non-empty string as disabled)
    from .. import envs
    if envs.get("MXTPU_DISABLE_FLASH"):
        return False
    from . import flash_attention as fa
    if not fa._INTERPRET:
        from ..base import on_accelerator
        if not on_accelerator():
            return False
    d = q.shape[-1]
    if q.shape[2] % k.shape[2]:
        return False  # ragged head grouping
    return d % 8 == 0 and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0


@register("interleaved_matmul_selfatt_qk", num_inputs=1)
def interleaved_matmul_selfatt_qk(queries_keys_values, *, heads=1):
    """Reference contrib op (transformer.cc): input (S, B, 3*E) packed
    QKV interleaved per head; returns (B*heads, S, S) scores."""
    s, b, e3 = queries_keys_values.shape
    e = e3 // 3
    qkv = queries_keys_values.reshape(s, b, heads, 3, e // heads)
    q = qkv[:, :, :, 0]
    k = qkv[:, :, :, 1]
    scores = jnp.einsum("sbhd,tbhd->bhst", q, k)
    scale = 1.0 / np.sqrt(e // heads)
    return (scores * scale).reshape(b * heads, s, s)


@register("interleaved_matmul_selfatt_valatt", num_inputs=2)
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, *,
                                      heads=1):
    s, b, e3 = queries_keys_values.shape
    e = e3 // 3
    qkv = queries_keys_values.reshape(s, b, heads, 3, e // heads)
    v = qkv[:, :, :, 2]
    att = attention.reshape(b, heads, s, s)
    out = jnp.einsum("bhst,tbhd->sbhd", att, v)
    return out.reshape(s, b, e)


@register("rope", num_inputs=1, scalar_attrs=("offset",),
          scalar_ref_input=None)
def rope(x, offset=0, *, base=10000.0):
    """Rotary position embedding over (B, S, H, D) — rotates adjacent
    feature pairs by position-dependent angles (Llama-family attention;
    no reference analogue, the reference predates RoPE).

    ``offset`` shifts positions (decode-time KV-cache continuation); it
    is a dynamic scalar attr so a generation loop stepping offset
    0,1,2,... reuses one compiled executable instead of recompiling
    per position.  A (B,)-shaped offset gives every batch row its OWN
    position — the continuous-batching decode shape, where each serving
    slot sits at a different depth in its sequence.
    """
    s, d = x.shape[1], x.shape[-1]
    off = jnp.asarray(offset, jnp.float32)
    base_pos = jnp.arange(s, dtype=jnp.float32)
    if off.ndim:
        pos = base_pos[None, :] + off.reshape(-1, 1)   # (B, S)
    else:
        # scalar path: keep the exact historical fp sequence (add THEN
        # broadcast) so offset-scalar callers stay bit-identical
        pos = (base_pos + off)[None, :]                # (1, S)
    inv = jnp.power(
        jnp.float32(base),
        -jnp.arange(0, d, 2, dtype=jnp.float32) / jnp.float32(d))
    ang = pos[..., None] * inv                         # (B|1, S, D/2)
    cos = jnp.cos(ang)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, :, None, :].astype(x.dtype)
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    # re-interleave pairs: (..., D/2, 2) -> (..., D)
    return jnp.stack([r1, r2], axis=-1).reshape(x.shape)
