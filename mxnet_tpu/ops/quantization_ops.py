"""INT8 quantization operators (reference ``src/operator/quantization/``
``quantize-inl.h`` / ``dequantize-inl.h`` / ``requantize-inl.h`` —
SURVEY.md §2.2 quantization row).

Reference semantics, TPU spelling: symmetric int8 against the signed
range; the (min, max) companions travel as 1-element float arrays, the
reference's layout for threading calibration through a graph.  XLA maps
int8 matmul/conv operands onto native MXU int8 ops, so quantize →
int8-compute → requantize chains compile to the hardware path.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

_INT8_MAX = 127.0
_INT32_MAX = float(2 ** 31 - 1)


def _real_range(min_r, max_r):
    return jnp.maximum(jnp.max(jnp.abs(min_r)), jnp.max(jnp.abs(max_r)))


@register("_contrib_quantize", num_inputs=3, num_outputs=3)
def quantize(data, min_range, max_range, *, out_type="int8"):
    """fp32 → (int8, min_out, max_out); symmetric against
    max(|min_range|, |max_range|)."""
    if out_type != "int8":
        raise ValueError("only int8 quantization is supported on TPU")
    r = _real_range(min_range, max_range)
    scale = jnp.where(r > 0, _INT8_MAX / jnp.maximum(r, 1e-30), 1.0)
    q = jnp.clip(jnp.round(data.astype(jnp.float32) * scale),
                 -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q, -r.reshape(1), r.reshape(1)


@register("_contrib_dequantize", num_inputs=3)
def dequantize(data, min_range, max_range, *, out_type="float32"):
    if out_type != "float32":
        raise ValueError("only float32 dequantization is supported")
    r = _real_range(min_range, max_range)
    return data.astype(jnp.float32) * (r / _INT8_MAX)


@register("_contrib_requantize", num_inputs=3, num_outputs=3)
def requantize(data, min_range, max_range, *, min_calib_range=None,
               max_calib_range=None):
    """int32 accumulator → int8 (reference requantize-inl.h).

    ``data`` is the int32 result of an int8×int8 matmul/conv whose real
    value is ``data * real_range/(2^31-1)``.  With a calibrated range
    the rescale is static (the fast path the reference's calibration
    exists for); otherwise the range is computed from the data.
    Returns (int8, min_out, max_out).
    """
    if (min_calib_range is None) != (max_calib_range is None):
        raise ValueError(
            "requantize: min_calib_range and max_calib_range must be "
            "given together (a half-supplied pair would silently fall "
            "back to dynamic ranges)")
    in_r = _real_range(min_range, max_range)
    in_scale = in_r / _INT32_MAX
    real = data.astype(jnp.float32) * in_scale
    if min_calib_range is not None and max_calib_range is not None:
        out_r = jnp.maximum(jnp.abs(jnp.float32(min_calib_range)),
                            jnp.abs(jnp.float32(max_calib_range)))
    else:
        out_r = jnp.max(jnp.abs(real))
    out_r = jnp.maximum(out_r, 1e-30)
    q = jnp.clip(jnp.round(real * (_INT8_MAX / out_r)),
                 -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q, -out_r.reshape(1), out_r.reshape(1)
