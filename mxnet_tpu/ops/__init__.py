"""Operator registry package (nnvm-registry equivalent, SURVEY.md §2.2).

Importing this package registers every operator.  New operator modules must
be imported here to appear in the ``mx.nd`` / ``mx.sym`` namespaces.
"""
from . import registry
from .registry import register, get_op, list_ops, alias
from . import tensor  # noqa: F401  (registers tensor ops)
from . import nn      # noqa: F401  (registers NN ops)
from . import random_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import attention  # noqa: F401  (fused SDPA + contrib transformer)
from . import det     # noqa: F401  (roi_align / box_nms / box_iou)
from . import moe     # noqa: F401  (expert-parallel MoE FFN)
from . import quantization_ops  # noqa: F401  (int8 quantize family)
