"""Tensor operators (elemwise / broadcast / reduce / matrix / init / ordering).

Capability parity: reference ``src/operator/tensor/`` — elemwise_unary_op*,
elemwise_binary_op*, broadcast_reduce_op*, matrix_op*, init_op*, ordering_op*,
indexing_op* (SURVEY.md §2.2).  Each op here is a pure JAX function; XLA
supplies the kernels, fusion and layout, so ~60k LoC of mshadow template
kernels in the reference collapse into jnp/lax calls with MXNet's names,
attributes and numerics (reduce ``exclude``, dot's last-first contraction,
reshape magic codes, ...).

MXNet numerics notes honoured here (SURVEY.md §7 hard-part 4):
  * elemwise ops do NOT implicitly broadcast — the ``broadcast_*`` family
    does; the NDArray operator sugar maps ``+`` to broadcast_add etc.
  * default dtype is float32 everywhere.
  * reductions keep dtype (no NumPy int upcasting).
"""
from __future__ import annotations

import builtins
import functools
import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _norm_axis(axis, ndim, exclude=False):
    """Normalize MXNet reduce axis attr (None/int/tuple, exclude flag)."""
    if axis is None or axis == ():
        axes = tuple(range(ndim))
    elif isinstance(axis, int):
        axes = (axis % ndim,)
    else:
        axes = tuple(a % ndim for a in axis)
    if exclude:
        axes = tuple(a for a in range(ndim) if a not in axes)
    return axes


def _reduce(fn, data, *, axis, keepdims, exclude):
    axes = _norm_axis(axis, data.ndim, exclude)
    return fn(data, axis=axes, keepdims=keepdims)


# ---------------------------------------------------------------------------
# init ops (no tensor inputs): zeros / ones / full / arange / eye
# reference: src/operator/tensor/init_op.{h,cc}
# ---------------------------------------------------------------------------


@register("_zeros", num_inputs=0, wrap_ctx=True)
def _zeros(*, shape=(), dtype="float32"):
    return jnp.zeros(shape, dtype=dtype)


@register("_ones", num_inputs=0, wrap_ctx=True)
def _ones(*, shape=(), dtype="float32"):
    return jnp.ones(shape, dtype=dtype)


@register("_full", num_inputs=0, wrap_ctx=True)
def _full(*, shape=(), value=0.0, dtype="float32"):
    return jnp.full(shape, value, dtype=dtype)


@register("_arange", num_inputs=0, wrap_ctx=True)
def _arange(*, start=0.0, stop=None, step=1.0, repeat=1, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype=dtype)
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_eye", num_inputs=0, wrap_ctx=True)
def _eye(*, N=0, M=0, k=0, dtype="float32"):
    return jnp.eye(N, M if M else None, k=k, dtype=dtype)


@register("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


# ---------------------------------------------------------------------------
# elemwise unary — reference elemwise_unary_op_basic.cc etc.
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "rint": jnp.rint,
    "ceil": jnp.ceil, "floor": jnp.floor, "trunc": jnp.trunc,
    "fix": jnp.trunc, "square": jnp.square, "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x), "cbrt": jnp.cbrt,
    "exp": jnp.exp, "log": jnp.log, "log10": jnp.log10,
    "log2": jnp.log2, "log1p": jnp.log1p, "expm1": jnp.expm1,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "erf": jax.scipy.special.erf, "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "negative": jnp.negative, "reciprocal": jnp.reciprocal,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype),
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "round": jnp.round,
}

for _name, _fn in _UNARY.items():
    register(_name)(functools.partial(lambda x, _f=None: _f(x), _f=_fn))


@register("rcbrt")
def rcbrt(x):
    return 1.0 / jnp.cbrt(x)


@register("degrees")
def degrees(x):
    return jnp.degrees(x)


@register("radians")
def radians(x):
    return jnp.radians(x)


@register("_copy")
def _copy(x):
    return x + jnp.zeros((), x.dtype) if jnp.issubdtype(x.dtype, jnp.number) else jnp.array(x)


@register("cast")
def cast(x, *, dtype="float32"):
    return x.astype(dtype)


@register("clip", scalar_attrs=("a_min", "a_max"))
def clip(x, a_min, a_max):
    return jnp.clip(x, a_min, a_max)


# ---------------------------------------------------------------------------
# scalar arithmetic (dynamic scalar passed as trailing 0-d array so that the
# compile cache does not key on the value)
# ---------------------------------------------------------------------------

_SCALAR_BIN = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(s, x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
}

for _name, _fn in _SCALAR_BIN.items():
    # the positional param carrying the dynamic scalar must be NAMED
    # "scalar" to match scalar_attrs (register() enforces this: the
    # frontend maps scalar kwargs/defaults to positions by name)
    register(_name, num_inputs=1, scalar_attrs=("scalar",))(
        functools.partial(lambda x, scalar, _f=None: _f(x, scalar),
                          _f=_fn))


# ---------------------------------------------------------------------------
# broadcast binary — reference elemwise_binary_broadcast_op*.cc
# ---------------------------------------------------------------------------

_BROADCAST_BIN = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "broadcast_equal": lambda a, b: (a == b).astype(a.dtype),
    "broadcast_not_equal": lambda a, b: (a != b).astype(a.dtype),
    "broadcast_greater": lambda a, b: (a > b).astype(a.dtype),
    "broadcast_greater_equal": lambda a, b: (a >= b).astype(a.dtype),
    "broadcast_lesser": lambda a, b: (a < b).astype(a.dtype),
    "broadcast_lesser_equal": lambda a, b: (a <= b).astype(a.dtype),
    "broadcast_logical_and": lambda a, b: jnp.logical_and(a, b).astype(a.dtype),
    "broadcast_logical_or": lambda a, b: jnp.logical_or(a, b).astype(a.dtype),
    "broadcast_logical_xor": lambda a, b: jnp.logical_xor(a, b).astype(a.dtype),
}

for _name, _fn in _BROADCAST_BIN.items():
    register(_name, num_inputs=2)(
        functools.partial(lambda a, b, _f=None: _f(a, b), _f=_fn))

# strict (same-shape) elemwise variants, MXNet internal names
for _name, _canon in [("elemwise_add", jnp.add), ("elemwise_sub", jnp.subtract),
                      ("elemwise_mul", jnp.multiply), ("elemwise_div", jnp.divide)]:
    register(_name, num_inputs=2)(
        functools.partial(lambda a, b, _f=None: _f(a, b), _f=_canon))


# ---------------------------------------------------------------------------
# reductions — reference broadcast_reduce_op*.cc.  MXNet attrs: axis (int or
# tuple), keepdims, exclude.
# ---------------------------------------------------------------------------

def _make_reduce(jfn):
    def fcompute(data, *, axis=None, keepdims=False, exclude=False):
        return _reduce(jfn, data, axis=axis, keepdims=keepdims,
                       exclude=exclude)
    return fcompute


for _name, _jfn in [("sum", jnp.sum), ("mean", jnp.mean), ("prod", jnp.prod),
                    ("max", jnp.max), ("min", jnp.min),
                    ("nansum", jnp.nansum), ("nanprod", jnp.nanprod)]:
    register(_name)(_make_reduce(_jfn))

alias("sum_axis", "sum")


@register("norm")
def norm(data, *, ord=2, axis=None, keepdims=False):
    axes = None if axis is None else _norm_axis(axis, data.ndim)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=axes, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=keepdims))


@register("argmax")
def argmax(data, *, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype("float32")  # MXNet returns float32 indices


@register("argmin")
def argmin(data, *, axis=None, keepdims=False):
    return jnp.argmin(data, axis=axis, keepdims=keepdims).astype("float32")


@register("argmax_channel")
def argmax_channel(data):
    return jnp.argmax(data, axis=1).astype("float32")


# ---------------------------------------------------------------------------
# matrix / shape ops — reference matrix_op*.cc, dot.cc
# ---------------------------------------------------------------------------


def _int8_acc(a, b):
    """int8×int8 contractions accumulate in int32 (the MXU-native
    quantized path, reference quantized_dot/quantized_conv semantics):
    the HLO must carry s8 operands with an s32 result — upcasting the
    OPERANDS to s32 first would both overflow-differ from the
    reference and miss the MXU int8 units."""
    return (jnp.int32 if a.dtype == jnp.int8 and b.dtype == jnp.int8
            else None)


@register("dot", num_inputs=2)
def dot(a, b, *, transpose_a=False, transpose_b=False):
    """MXNet dot: contract LAST axis of a with FIRST axis of b."""
    if transpose_a:
        a = jnp.transpose(a)
    if transpose_b:
        b = jnp.transpose(b)
    return jnp.tensordot(a, b, axes=1,
                         preferred_element_type=_int8_acc(a, b))


@register("batch_dot", num_inputs=2)
def batch_dot(a, b, *, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b, preferred_element_type=_int8_acc(a, b))


@register("linalg_gemm2", num_inputs=2)
def linalg_gemm2(a, b, *, transpose_a=False, transpose_b=False, alpha=1.0):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


def _reshape_target(shape_attr: Tuple[int, ...], src: Tuple[int, ...],
                    reverse=False):
    """Implement MXNet reshape magic codes 0, -1, -2, -3, -4."""
    if reverse:
        shape_attr = tuple(reversed(shape_attr))
        src = tuple(reversed(src))
    out = []
    src_i = 0
    i = 0
    attr = list(shape_attr)
    while i < len(attr):
        d = attr[i]
        if d == 0:
            out.append(src[src_i]); src_i += 1
        elif d == -1:
            out.append(-1); src_i += 1
        elif d == -2:
            out.extend(src[src_i:]); src_i = len(src)
        elif d == -3:
            out.append(src[src_i] * src[src_i + 1]); src_i += 2
        elif d == -4:
            d1, d2 = attr[i + 1], attr[i + 2]
            cur = src[src_i]; src_i += 1
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2]); i += 2
        else:
            out.append(d); src_i += 1
        i += 1
    if reverse:
        out = list(reversed(out))
    return tuple(out)


@register("reshape")
def reshape(data, *, shape=(), reverse=False):
    return jnp.reshape(data, _reshape_target(tuple(shape), data.shape,
                                             reverse))


alias("Reshape", "reshape")


@register("transpose")
def transpose(data, *, axes=()):
    return jnp.transpose(data, axes if axes else None)


@register("expand_dims")
def expand_dims(data, *, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze")
def squeeze(data, *, axis=None):
    return jnp.squeeze(data, axis)


@register("flatten")
def flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


alias("Flatten", "flatten")


@register("broadcast_to")
def broadcast_to(data, *, shape=()):
    # MXNet semantics: 0 in target shape means "keep source dim"
    tgt = tuple(s if t == 0 else t for t, s in zip(shape, data.shape)) \
        if len(shape) == data.ndim else tuple(shape)
    return jnp.broadcast_to(data, tgt)


@register("broadcast_axis")
def broadcast_axis(data, *, axis=(), size=()):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register("broadcast_like", num_inputs=2)
def broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)


@register("slice")
def slice_op(data, *, begin=(), end=(), step=()):
    nd = data.ndim
    begin = tuple(begin) + (None,) * (nd - len(begin))
    end = tuple(end) + (None,) * (nd - len(end))
    step = tuple(step) + (None,) * (nd - len(step)) if step else (None,) * nd
    idx = tuple(builtins.slice(b, e, s) for b, e, s in zip(begin, end, step))
    return data[idx]


@register("slice_axis")
def slice_axis(data, *, axis=0, begin=0, end=None):
    idx = [builtins.slice(None)] * data.ndim
    idx[axis] = builtins.slice(begin, end)
    return data[tuple(idx)]


@register("slice_like", num_inputs=2)
def slice_like(data, shape_like, *, axes=()):
    axes = tuple(axes) if axes else tuple(range(shape_like.ndim))
    idx = [builtins.slice(None)] * data.ndim
    for a in axes:
        idx[a] = builtins.slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("concat", num_inputs=None)
def concat(*args, dim=1):
    return jnp.concatenate(args, axis=dim)


alias("Concat", "concat")


@register("stack", num_inputs=None)
def stack(*args, axis=0):
    return jnp.stack(args, axis=axis)


@register("split", num_outputs=-1)
def split(data, *, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


alias("SliceChannel", "split")


@register("take", num_inputs=2)
def take(a, indices, *, axis=0, mode="clip"):
    if mode == "raise":
        raise NotImplementedError(
            "take(mode='raise'): data-dependent bounds checking cannot run "
            "inside a compiled XLA program; use mode='clip' or 'wrap' "
            "(documented capability gap)")
    idx = indices.astype("int32")
    if mode == "clip":
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    elif mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    return jnp.take(a, idx, axis=axis)


@register("pick", num_inputs=2)
def pick(data, index, *, axis=-1, keepdims=False, mode="clip"):
    if mode == "raise":
        raise NotImplementedError(
            "pick(mode='raise'): use mode='clip' or 'wrap' (no "
            "data-dependent raising inside compiled XLA programs)")
    if mode == "wrap":
        idx = jnp.mod(index.astype("int32"), data.shape[axis])
    else:
        idx = jnp.clip(index.astype("int32"), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("embedding", num_inputs=2)
def embedding(data, weight, *, input_dim=0, output_dim=0, dtype="float32",
              sparse_grad=False):
    """reference: src/operator/tensor/indexing_op.cc (Embedding)."""
    return jnp.take(weight, data.astype("int32"), axis=0)


alias("Embedding", "embedding")


@register("gather_nd", num_inputs=2)
def gather_nd(data, indices):
    idx = tuple(indices.astype("int32"))
    return data[idx]


@register("one_hot")
def one_hot(indices, *, depth=0, on_value=1.0, off_value=0.0,
            dtype="float32"):
    return jax.nn.one_hot(indices.astype("int32"), depth,
                          dtype=dtype) * (on_value - off_value) + off_value


@register("tile")
def tile(data, *, reps=()):
    return jnp.tile(data, tuple(reps))


@register("repeat")
def repeat(data, *, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("reverse")
def reverse(data, *, axis=()):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, axis=axes)


alias("flip", "reverse")


@register("where", num_inputs=3)
def where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@register("diag")
def diag(data, *, k=0):
    if data.ndim == 1:
        return jnp.diag(data, k=k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


@register("swapaxes")
def swapaxes(data, *, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


alias("SwapAxis", "swapaxes")


@register("depth_to_space")
def depth_to_space(data, *, block_size=1):
    n, c, h, w = data.shape
    b = block_size
    x = jnp.reshape(data, (n, b, b, c // (b * b), h, w))
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return jnp.reshape(x, (n, c // (b * b), h * b, w * b))


@register("space_to_depth")
def space_to_depth(data, *, block_size=1):
    n, c, h, w = data.shape
    b = block_size
    x = jnp.reshape(data, (n, c, h // b, b, w // b, b))
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return jnp.reshape(x, (n, c * b * b, h // b, w // b))


@register("pad")
def pad(data, *, mode="constant", pad_width=(), constant_value=0.0):
    pw = tuple(pad_width)
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    jmode = {"constant": "constant", "edge": "edge",
             "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pairs, mode=jmode,
                       constant_values=constant_value)
    return jnp.pad(data, pairs, mode=jmode)


alias("Pad", "pad")


# ---------------------------------------------------------------------------
# ordering ops — reference ordering_op.cc
# ---------------------------------------------------------------------------


@register("sort")
def sort(data, *, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort")
def argsort(data, *, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(dtype)


@register("topk", num_outputs=-1)
def topk(data, *, axis=-1, k=1, ret_typ="indices", is_ascend=False,
         dtype="float32"):
    src = -data if is_ascend else data
    if axis != -1 and axis != data.ndim - 1:
        src = jnp.moveaxis(src, axis, -1)
    vals, idx = lax.top_k(src, k)
    if is_ascend:
        vals = -vals
    if axis != -1 and axis != data.ndim - 1:
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx.astype(dtype)
    return idx.astype(dtype)


# ---------------------------------------------------------------------------
# sequence ops — reference src/operator/sequence_*.cc
# ---------------------------------------------------------------------------


@register("SequenceMask", num_inputs=None)
def sequence_mask(data, *rest, use_sequence_length=False, value=0.0, axis=0):
    if not use_sequence_length:
        return data
    seqlen = rest[0]
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    if axis == 0:
        mask = steps[:, None] < seqlen[None, :].astype("int32")
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    else:
        mask = steps[None, :] < seqlen[:, None].astype("int32")
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, value)


@register("SequenceLast", num_inputs=None)
def sequence_last(data, *rest, use_sequence_length=False, axis=0):
    if not use_sequence_length:
        idx = [builtins.slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    seqlen = rest[0].astype("int32") - 1
    data_t = jnp.moveaxis(data, axis, 0)
    batch = jnp.arange(data_t.shape[1])
    return data_t[seqlen, batch]


@register("SequenceReverse", num_inputs=None)
def sequence_reverse(data, *rest, use_sequence_length=False, axis=0):
    if not use_sequence_length:
        return jnp.flip(data, axis=0)
    seqlen = rest[0].astype("int32")
    T = data.shape[0]
    steps = jnp.arange(T)[:, None]
    rev_idx = jnp.where(steps < seqlen[None, :], seqlen[None, :] - 1 - steps,
                        steps)
    batch = jnp.arange(data.shape[1])[None, :]
    return data[rev_idx, batch]


# ---------------------------------------------------------------------------
# variadic sum — reference src/operator/tensor/elemwise_sum.cc
# ---------------------------------------------------------------------------


@register("add_n", num_inputs=None)
def add_n(*arrays):
    out = arrays[0]
    for a in arrays[1:]:
        out = out + a
    return out


alias("ElementWiseSum", "add_n")


@register("square_sum")
def square_sum(data, *, axis=None, keepdims=False, exclude=False):
    return _reduce(lambda d, axis, keepdims: jnp.sum(jnp.square(d),
                                                     axis=axis,
                                                     keepdims=keepdims),
                   data, axis=axis, keepdims=keepdims, exclude=exclude)


@register("log_sum_exp")
def log_sum_exp(data, *, axis=None, keepdims=False):
    axes = None if axis is None else _norm_axis(axis, data.ndim)
    return jax.nn.logsumexp(data, axis=axes, keepdims=keepdims)


# ---------------------------------------------------------------------------
# round-2 gap closure: remaining reference tensor/linalg ops
# (reference src/operator/tensor/{matrix_op,ordering_op,init_op}.cc,
#  src/operator/tensor/la_op.cc, src/operator/contrib/krprod.cc)
# ---------------------------------------------------------------------------


@register("cumsum")
def cumsum(a, *, axis=None, dtype=None):
    out = jnp.cumsum(a if axis is not None else a.ravel(),
                     axis=axis if axis is not None else 0)
    return out.astype(dtype) if dtype else out


@register("cumprod")
def cumprod(a, *, axis=None, dtype=None):
    out = jnp.cumprod(a if axis is not None else a.ravel(),
                      axis=axis if axis is not None else 0)
    return out.astype(dtype) if dtype else out


@register("trace")
def trace(data, *, offset=0, axis1=0, axis2=1):
    return jnp.trace(data, offset=offset, axis1=axis1, axis2=axis2)


@register("triu")
def triu(data, *, k=0):
    return jnp.triu(data, k=k)


@register("tril")
def tril(data, *, k=0):
    return jnp.tril(data, k=k)


@register("roll")
def roll(data, *, shift=0, axis=None):
    shift = tuple(shift) if isinstance(shift, (tuple, list)) else shift
    axis = tuple(axis) if isinstance(axis, (tuple, list)) else axis
    return jnp.roll(data, shift, axis=axis)


@register("linspace", num_inputs=0, wrap_ctx=True)
def linspace(*, start=0.0, stop=1.0, num=50, endpoint=True,
             dtype="float32"):
    return jnp.linspace(start, stop, int(num), endpoint=endpoint,
                        dtype=dtype)


@register("logspace", num_inputs=0, wrap_ctx=True)
def logspace(*, start=0.0, stop=1.0, num=50, endpoint=True, base=10.0,
             dtype="float32"):
    return jnp.logspace(start, stop, int(num), endpoint=endpoint,
                        base=base, dtype=dtype)


@register("hard_sigmoid")
def hard_sigmoid(data, *, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("digamma")
def digamma(data):
    import jax.scipy.special as jsp
    return jsp.digamma(data)


@register("smooth_l1")
def smooth_l1(data, *, scalar=1.0):
    """Reference smooth_l1: transition point at 1/scalar**2."""
    s2 = scalar * scalar
    a = jnp.abs(data)
    return jnp.where(a < 1.0 / s2, 0.5 * s2 * data * data,
                     a - 0.5 / s2)


@register("batch_take", num_inputs=2)
def batch_take(a, indices):
    """a (N, K), indices (N,) → picks a[i, indices[i]] per row."""
    idx = indices.astype("int32")
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register("scatter_nd", num_inputs=2)
def scatter_nd(data, indices, *, shape=()):
    """Reference scatter_nd: indices (M, N) leading coords for N data
    items into an output of ``shape``.  Duplicate indices are
    implementation-defined (as in the reference)."""
    out = jnp.zeros(tuple(shape), data.dtype)
    idx = tuple(indices.astype("int32"))
    return out.at[idx].set(data)


@register("gather_nd_raw", num_inputs=2)
def gather_nd_raw(data, indices):
    idx = tuple(indices.astype("int32"))
    return data[idx]


@register("ravel_multi_index")
def ravel_multi_index(data, *, shape=()):
    """data (N, M): N coordinate rows → (M,) flat indices."""
    dims = jnp.asarray(shape, jnp.int32)
    idx = data.astype(jnp.int32)
    # strides[i] = prod(dims[i+1:]); last stride is 1
    rev_cp = jnp.cumprod(dims[::-1])
    strides = jnp.concatenate(
        [rev_cp[-2::-1], jnp.ones((1,), dims.dtype)])
    return (idx * strides[:, None]).sum(axis=0).astype(data.dtype)


@register("unravel_index")
def unravel_index(data, *, shape=()):
    """(M,) flat indices → (N, M) coordinate rows."""
    idx = data.astype(jnp.int32)
    coords = jnp.stack(jnp.unravel_index(idx, tuple(shape)))
    return coords.astype(data.dtype)


@register("khatri_rao", num_inputs=None)
def khatri_rao(*mats):
    """Column-wise Kronecker product (reference contrib krprod.cc):
    inputs (r_i, k) → output (prod r_i, k)."""
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(
            -1, out.shape[-1])
    return out


# -- linalg family (reference la_op.cc; mshadow-lapack there, XLA here) ----


@register("linalg_potrf")
def linalg_potrf(a):
    """Cholesky factor (lower), batched."""
    return jnp.linalg.cholesky(a)


@register("linalg_potri")
def linalg_potri(a):
    """Inverse from the Cholesky factor: inv(L Lᵀ)."""
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
    linv = jax.scipy.linalg.solve_triangular(a, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("linalg_syrk")
def linalg_syrk(a, *, transpose=False, alpha=1.0):
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose
                    else jnp.matmul(a, at))


@register("linalg_trmm", num_inputs=2)
def linalg_trmm(a, b, *, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    tri = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    return alpha * (jnp.matmul(b, tri) if rightside
                    else jnp.matmul(tri, b))


@register("linalg_trsm", num_inputs=2)
def linalg_trsm(a, b, *, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Solve op(A) X = alpha B (or X op(A) = alpha B with rightside),
    A triangular; op(A) = Aᵀ when transpose."""
    import jax.scipy.linalg as jsl
    if rightside:
        # X op(A) = alpha B  →  op(A)ᵀ Xᵀ = alpha Bᵀ
        opat = a if transpose else jnp.swapaxes(a, -1, -2)
        low = lower if transpose else not lower
        xt = jsl.solve_triangular(opat, jnp.swapaxes(alpha * b, -1, -2),
                                  lower=low)
        return jnp.swapaxes(xt, -1, -2)
    opa = jnp.swapaxes(a, -1, -2) if transpose else a
    low = (not lower) if transpose else lower
    return jsl.solve_triangular(opa, alpha * b, lower=low)


@register("linalg_gelqf", num_outputs=2)
def linalg_gelqf(a):
    """LQ factorization: A = L Q with Q orthonormal rows."""
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("linalg_sumlogdiag")
def linalg_sumlogdiag(a):
    diag = jnp.diagonal(a, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


alias("power", "broadcast_power")
alias("logical_and", "broadcast_logical_and")
alias("logical_or", "broadcast_logical_or")
alias("logical_xor", "broadcast_logical_xor")


@register("_slice_basic")
def _slice_basic(x, *, key=()):
    """Differentiable basic indexing (tape path for NDArray.__getitem__
    under autograd.record; outside recording, views serve reads).

    key: per-axis entries ('s', start, stop, step), ('i', index),
    ('e',) for Ellipsis, or ('n',) for None/newaxis; trailing axes are
    implicitly full slices.
    """
    def dec(e):
        if e[0] == "s":
            return builtins.slice(e[1], e[2], e[3])
        if e[0] == "e":
            return Ellipsis
        if e[0] == "n":
            return None
        return int(e[1])

    return x[tuple(dec(e) for e in key)]


@register("_cache_update", num_inputs=2, scalar_attrs=("offset",),
          scalar_ref_input=None)
def _cache_update(cache, new, offset=0):
    """Write ``new`` into ``cache`` at position ``offset`` along axis 1
    (KV-cache decode).  ``offset`` is a dynamic scalar attr so every
    decode step reuses ONE compiled scatter instead of compiling a new
    program per position.  A (B,)-shaped offset scatters each batch
    row at its OWN position (per-slot decode in the serving plane)."""
    off = jnp.asarray(offset, jnp.int32)
    if off.ndim:
        import jax
        return jax.vmap(
            lambda c, n, o: lax.dynamic_update_slice_in_dim(
                c, n.astype(c.dtype), o, axis=0)
        )(cache, new, off.reshape(-1))
    return lax.dynamic_update_slice_in_dim(
        cache, new.astype(cache.dtype), off, axis=1)


@register("_contrib_arange_like", num_inputs=1)
def arange_like(data, *, start=0.0, step=1.0, repeat=1, axis=None):
    """Arange shaped like ``data`` (parity: mx.nd.contrib.arange_like;
    hybridizable position indices without a shape-dependent constant).
    """
    # repeat holds each value ``repeat`` times WITHIN the output
    # length (reference semantics: total length stays n)
    if axis is None:
        n = 1
        for d in data.shape:
            n *= d
        out = start + step * (jnp.arange(n) // repeat)
        return out.reshape(data.shape).astype(data.dtype)
    n = data.shape[axis]
    return (start + step * (jnp.arange(n) // repeat)) \
        .astype(data.dtype)


@register("_contrib_index_array", num_inputs=1)
def index_array(data, *, axes=None):
    """Per-element N-D indices of ``data`` (parity:
    mx.nd.contrib.index_array): output (*data.shape, len(axes))."""
    shape = data.shape
    sel = tuple(range(len(shape))) if axes is None else tuple(axes)
    grids = jnp.meshgrid(*[jnp.arange(s) for s in shape],
                         indexing="ij")
    return jnp.stack([grids[a] for a in sel], axis=-1).astype("int32")


@register("_contrib_index_copy", num_inputs=3)
def index_copy(old, index, new):
    """Copy rows of ``new`` into ``old`` at ``index`` along axis 0
    (parity: mx.nd.contrib.index_copy; out-of-place like the
    reference's functional form)."""
    return old.at[index.astype(jnp.int32)].set(new.astype(old.dtype))


@register("_contrib_AdaptiveAvgPooling2D", num_inputs=1)
def adaptive_avg_pooling(data, *, output_size=()):
    """NCHW adaptive average pooling to ``output_size`` (parity:
    mx.nd.contrib.AdaptiveAvgPooling2D; reference
    ``src/operator/contrib/adaptive_avg_pooling.cc``).  Matches the
    reference's variable-window semantics (start = floor(i*H/h'),
    end = ceil((i+1)*H/h')) via a normalized matmul per axis — dense
    MXU work instead of ragged windows.
    """
    b, c, h, w = data.shape
    if not output_size:
        oh = ow = 1
    elif isinstance(output_size, int):
        oh = ow = int(output_size)
    elif len(output_size) == 1:
        oh = ow = int(output_size[0])
    else:
        oh, ow = int(output_size[0]), int(output_size[1])

    def pool_matrix(n_in, n_out):
        i = jnp.arange(n_out)
        starts = jnp.floor(i * n_in / n_out).astype(jnp.int32)
        ends = jnp.ceil((i + 1) * n_in / n_out).astype(jnp.int32)
        pos = jnp.arange(n_in)
        m = ((pos[None, :] >= starts[:, None])
             & (pos[None, :] < ends[:, None])).astype(data.dtype)
        return m / m.sum(axis=1, keepdims=True)

    mh = pool_matrix(h, oh)                     # (oh, h)
    mw = pool_matrix(w, ow)                     # (ow, w)
    out = jnp.einsum("oh,bchw->bcow", mh, data)
    return jnp.einsum("pw,bcow->bcop", mw, out)
