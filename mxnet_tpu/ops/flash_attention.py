"""Pallas flash-attention kernels for TPU (forward AND backward).

Capability parity / perf: the reference leans on cuDNN fused attention
(contrib transformer ops); the TPU equivalent is a Pallas kernel that
streams K/V blocks through VMEM with an online-softmax accumulator, never
materializing the (S,S) score matrix in HBM (SURVEY.md §5 "Long-context",
pallas_guide.md tiling/grid sections).

Forward emits the per-row log-sum-exp alongside the output; backward is
the standard two-pass flash scheme (FlashAttention-2 layout):
  * pass 1 (grid BH×Qblk×Kblk): recompute P from the saved LSE, accumulate
    dQ += (P ∘ (dO Vᵀ − Δ)) K · scale in VMEM scratch;
  * pass 2 (grid BH×Kblk×Qblk): accumulate dV += Pᵀ dO and
    dK += (P ∘ (dO Vᵀ − Δ))ᵀ Q · scale;
with Δ = rowsum(dO ∘ O) computed once in XLA.  Neither pass materializes
(S,S) in HBM.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["flash_attention"]

_BLOCK_Q = 128
_BLOCK_K = 128
_LANE = 128  # TPU lane width: head_dim is zero-padded up to this


def _default_blocks(s_q, s_k):
    """Measured seq-adaptive tile defaults (bench_logs/r5/
    attention_blocks.log, v5e): 128x128 was the WORST row at every
    swept seq — bwd at 2048 runs 2.0x faster at 256x256 (10.46 →
    5.25 ms) and at 1024 1.7x faster at 128x512 (2.11 → 1.25 ms).
    Larger tiles amortize the dq/dkv revisits across the grid; VMEM
    stays comfortable (256x256 f32 scores = 256 KiB of ~16 MiB)."""
    s = max(s_q, s_k)
    if s >= 2048:
        want_q, want_k = 256, 256
    elif s >= 1024:
        want_q, want_k = 128, 512
    else:
        want_q, want_k = _BLOCK_Q, _BLOCK_K
    bq = want_q if s_q % want_q == 0 else _BLOCK_Q
    bk = want_k if s_k % want_k == 0 else _BLOCK_K
    return bq, bk


def _blocks(s_q, s_k):
    """(block_q, block_k) for this launch: env-tunable so the on-chip
    attention bench can sweep backward block sizes (the s>=1024 dq/dkv
    perf lever, VERDICT r3 #4) without rebuilding; unset or
    non-dividing values fall back to the measured seq-adaptive
    defaults (clamped to 128 when those don't divide either)."""
    from .. import envs
    dq, dk = _default_blocks(s_q, s_k)
    bq = envs.get("MXTPU_FLASH_BLOCK_Q") or dq
    bk = envs.get("MXTPU_FLASH_BLOCK_K") or dk
    if bq <= 0 or s_q % bq:
        bq = dq
    if bk <= 0 or s_k % bk:
        bk = dk
    return bq, bk

# interpret mode runs the kernel on the Pallas interpreter (any backend)
# — used by the CPU test suite; toggled via tests or MXTPU_FLASH_INTERPRET
# (typed read: '0'/'false' parse as off, unlike the old truthy-string)
from .. import envs as _envs
_INTERPRET = _envs.get("MXTPU_FLASH_INTERPRET")


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal,
                num_k_blocks, causal_offset, emit_lse, with_kmask,
                window=None):
    """One (batch*head, q-block, k-block) grid step.

    The k-block loop lives in the GRID (innermost dim, sequential on TPU)
    with the online-softmax state in VMEM scratch persisting across
    steps — the canonical Pallas flash layout, and it keeps every index
    static (dynamic in-kernel slices mis-lower under jax_enable_x64).
    """
    from jax.experimental import pallas as pl

    rest = list(rest)
    kmask_ref = rest.pop(0) if with_kmask else None
    o_ref = rest.pop(0)
    lse_ref = rest.pop(0) if emit_lse else None
    m_scr, l_scr, acc_scr = rest

    q_idx = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    q = q_ref[...]  # (block_q, d)
    k = k_ref[...]  # (block_k, d)
    v = v_ref[...]
    block_q, d = q.shape
    block_k = k.shape[0]

    def _accum():
        # operands stay in the input dtype (bf16 on the AMP path) so
        # the MXU runs at native rate; preferred_element_type keeps the
        # ACCUMULATOR f32 either way.  f32 inputs pin Precision.HIGHEST
        # explicitly: without it XLA's DEFAULT runs f32 matmuls at bf16
        # operand precision on TPU, making kernel numerics depend on the
        # ambient jax.default_matmul_precision context (the r3 on-chip
        # failures, bench_logs/r3/on_tpu_pytest.log).  Contract: f32 in
        # → f32-grade math, bf16 in → MXU-native ops + f32 accumulate.
        prec = (None if q.dtype == jnp.bfloat16
                else jax.lax.Precision.HIGHEST)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
                    precision=prec) * scale
        if causal:
            # end-aligned like the XLA oracle's tril(k=s_k-s_q): query
            # i may attend keys up to i + (s_k - s_q), so
            # cross-attention with s_k != s_q masks identically
            q_pos = q_idx * np.int32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * np.int32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            keep = q_pos + np.int32(causal_offset) >= k_pos
            if window is not None:
                # sliding window (Mistral-style band): query i attends
                # keys in (i+offset-W, i+offset]
                keep &= k_pos > q_pos + np.int32(causal_offset - window)
            s = jnp.where(keep, s, -1e30)
        if with_kmask:
            # key-padding mask row for this (batch, k-block): keep=True
            s = jnp.where(kmask_ref[...][:1] > 0, s, -1e30)

        # m/l scratch is (block_q, 128): TPU vector stores need a full
        # lane dim; value is replicated, column 0 is authoritative
        m = m_scr[...][:, :1]
        l = l_scr[...][:, :1]
        acc = acc_scr[...]
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        lanes = m_scr.shape[1]
        m_scr[...] = jnp.broadcast_to(m_new, (m_new.shape[0], lanes))
        l_new = alpha * l + p.sum(axis=1, keepdims=True)
        l_scr[...] = jnp.broadcast_to(l_new, (l_new.shape[0], lanes))
        # P rides the MXU in the value dtype when v is low-precision
        # (what the bf16 XLA oracle does too); f32 v keeps the f32 pass
        p_op = p.astype(v.dtype) if v.dtype == jnp.bfloat16 else p
        acc_scr[...] = alpha * acc + jnp.dot(
            p_op, v, preferred_element_type=jnp.float32, precision=prec)

    if causal and causal_offset >= 0:
        # block-level causal skip: a k-block whose FIRST key is beyond
        # the last query this q-block may attend is entirely masked —
        # skip its matmuls (≈2x less MXU work over the full grid, the
        # long-seq causal perf lever).  With offset >= 0 this is
        # EXACTLY the old math: kb=0 is always visible, so by the time
        # a skipped block would run, m is finite and its contribution
        # was p = exp(-1e30 - m) = 0, alpha = 1 — a no-op.  offset < 0
        # (causal cross-attention, s_q > s_k) keeps the full grid:
        # there a whole q-block can attend zero keys and skipping it
        # would leave l = 0 → 0/0 NaN where the oracle emits uniform
        # rows.
        visible = (q_idx * np.int32(block_q)
                   + np.int32(block_q - 1 + causal_offset)
                   >= kb * np.int32(block_k))
        if window is not None:
            # band's other edge: block dead once its LAST key falls at
            # or below the FIRST query's window floor — with offset>=0
            # every row still attends >= 1 key (its own diagonal), so
            # the skip stays division-safe.  This is what makes sliding
            # window O(S·W): only ~W/block_k + 1 k-blocks per q-block
            # survive, independent of S.
            visible &= (kb * np.int32(block_k) + np.int32(block_k - 1)
                        > q_idx * np.int32(block_q)
                        + np.int32(causal_offset - window))
        pl.when(visible)(_accum)
    else:
        _accum()

    @pl.when(kb == num_k_blocks - 1)
    def _done():
        o_ref[...] = (acc_scr[...] / l_scr[...][:, :1]).astype(
            o_ref.dtype)
        if emit_lse:
            # per-row log-sum-exp (lane-replicated), for the backward
            lse = m_scr[...][:, :1] + jnp.log(l_scr[...][:, :1])
            lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _blocked_specs(d, bq=_BLOCK_Q, bk=_BLOCK_K):
    from jax.experimental import pallas as pl

    # NOTE on index maps: with jax_enable_x64 a literal `0` in an index
    # map becomes i64 and Mosaic rejects the mixed (i32, i64) signature;
    # `i - i` keeps everything i32 regardless of the x64 flag.
    zero = lambda i: i - i
    q_spec = pl.BlockSpec((None, bq, d),
                          lambda i, j, kb: (i, j, zero(i)))
    k_spec = pl.BlockSpec((None, bk, d),
                          lambda i, j, kb: (i, kb, zero(i)))
    return zero, q_spec, k_spec


def _kmask_rows(kmask, s_k):
    """(B, S_k) key-padding mask → (B, 8, S_k) f32 rows (sublane-padded
    so the (8, block_k) tile satisfies TPU tiling; row 0 is read)."""
    m = kmask.astype(jnp.float32)[:, None, :]
    return jnp.broadcast_to(m, (m.shape[0], 8, s_k))


def _kmask_spec(h, kb_in_dim2=True, bk=_BLOCK_K):
    from jax.experimental import pallas as pl

    # grid dim 0 is b*h: batch index = i // h (static closure over h).
    # The k-block rides grid dim 2 (fwd, dq) or dim 1 (dkv).
    if kb_in_dim2:
        return pl.BlockSpec((None, 8, bk),
                            lambda i, j, kb: (i // h, j - j, kb))
    return pl.BlockSpec((None, 8, bk),
                        lambda i, kb, j: (i // h, j - j, kb))


def _fold(x, b, h, s, d):
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(x, b, h, s, d):
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_fwd_pallas(q, k, v, scale, causal, want_lse=True,
                      kmask=None, window=None):
    """q,k,v: (B, S, H, D) → (out (B, S, H, D), lse (B*H, S_q, 128) or
    None when ``want_lse=False`` — the inference path skips the LSE
    output entirely rather than writing HBM it will discard).

    head_dim < 128 (e.g. BERT's 64) is zero-padded up to the lane
    width: QKᵀ contracts over D so zero columns don't change scores,
    and PV leaves the padded output columns zero — sliced off at the
    end.  XLA would pad the minor dim to 128 on the MXU anyway, so the
    padding costs ~nothing on chip.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s_q, h, d_orig = q.shape
    s_k = k.shape[1]
    pad = (-d_orig) % _LANE
    if pad:
        widths = ((0, 0), (0, 0), (0, 0), (0, pad))
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    d = d_orig + pad
    qf = _fold(q, b, h, s_q, d)
    kf = _fold(k, b, h, s_k, d)
    vf = _fold(v, b, h, s_k, d)

    bq, bk = _blocks(s_q, s_k)
    num_k_blocks = s_k // bk
    grid = (b * h, s_q // bq, num_k_blocks)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               num_k_blocks=num_k_blocks,
                               causal_offset=s_k - s_q,
                               emit_lse=want_lse,
                               with_kmask=kmask is not None,
                               window=window)
    zero, q_spec, k_spec = _blocked_specs(d, bq, bk)
    lse_spec = pl.BlockSpec((None, bq, _LANE),
                            lambda i, j, kb: (i, j, zero(i)))
    in_specs = [q_spec, k_spec, k_spec]
    inputs = [qf, kf, vf]
    if kmask is not None:
        in_specs.append(_kmask_spec(h, bk=bk))
        inputs.append(_kmask_rows(kmask, s_k))
    out_specs = [q_spec, lse_spec] if want_lse else q_spec
    out_shape = [jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
                 jax.ShapeDtypeStruct((b * h, s_q, _LANE), jnp.float32)]
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape if want_lse else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(*inputs)
    out, lse = res if want_lse else (res, None)
    return _unfold(out, b, h, s_q, d)[..., :d_orig], lse


def _dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, *rest,
               scale, causal, num_k_blocks, causal_offset, with_kmask,
               window=None):
    from jax.experimental import pallas as pl

    rest = list(rest)
    kmask_ref = rest.pop(0) if with_kmask else None
    dq_ref, dq_scr = rest

    q_idx = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    # operands keep the input dtype (MXU-native on the bf16 path; f32
    # precision when inputs are f32) — accumulators are always f32
    q = q_ref[...]
    k = k_ref[...]
    v = v_ref[...]
    g = g_ref[...]
    lse = lse_ref[...][:, :1]
    delta = delta_ref[...][:, :1]
    block_q, _ = q.shape
    block_k = k.shape[0]
    lowp = q.dtype == jnp.bfloat16
    # same precision contract as the forward: f32 inputs pin HIGHEST
    prec = None if lowp else jax.lax.Precision.HIGHEST

    def _accum():
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
                    precision=prec) * scale
        mask = None
        if causal:
            q_pos = q_idx * np.int32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * np.int32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = q_pos + np.int32(causal_offset) >= k_pos
            if window is not None:
                mask &= k_pos > q_pos + np.int32(causal_offset - window)
            s_m = jnp.where(mask, s, -1e30)
        else:
            s_m = s
        if with_kmask:
            s_m = jnp.where(kmask_ref[...][:1] > 0, s_m, -1e30)
        p = jnp.exp(s_m - lse)
        if causal:
            # explicit zero (not exp of a huge negative) so fully-masked
            # rows contribute NO gradient instead of fp32-rounding noise
            p = jnp.where(mask, p, 0.0)
        if with_kmask:
            p = jnp.where(kmask_ref[...][:1] > 0, p, 0.0)
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32,
                     precision=prec)
        ds = p * (dp - delta.astype(jnp.float32))
        ds_op = ds.astype(jnp.bfloat16) if lowp else ds
        dq_scr[...] += jnp.dot(ds_op, k,
                               preferred_element_type=jnp.float32,
                               precision=prec) * scale

    if causal:
        # skip k-blocks this q-block cannot attend.  Safe for ANY
        # causal_offset (unlike the forward): a skipped block's
        # contribution was exactly zero — p is hard-zeroed by the
        # where(mask, p, 0) — so dq_scr is untouched either way.
        visible = (q_idx * np.int32(block_q)
                   + np.int32(block_q - 1 + causal_offset)
                   >= kb * np.int32(block_k))
        if window is not None:
            visible &= (kb * np.int32(block_k) + np.int32(block_k - 1)
                        > q_idx * np.int32(block_q)
                        + np.int32(causal_offset - window))
        pl.when(visible)(_accum)
    else:
        _accum()

    @pl.when(kb == num_k_blocks - 1)
    def _done():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, g_ref, lse_ref, delta_ref, *rest,
                scale, causal, num_q_blocks, causal_offset, with_kmask,
                window=None):
    from jax.experimental import pallas as pl

    rest = list(rest)
    kmask_ref = rest.pop(0) if with_kmask else None
    dk_ref, dv_ref, dk_scr, dv_scr = rest

    kb = pl.program_id(1)
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    k = k_ref[...]
    v = v_ref[...]
    q = q_ref[...]
    g = g_ref[...]
    lse = lse_ref[...][:, :1]
    delta = delta_ref[...][:, :1]
    block_k = k.shape[0]
    block_q = q.shape[0]
    lowp = q.dtype == jnp.bfloat16
    prec = None if lowp else jax.lax.Precision.HIGHEST

    def _accum():
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
                    precision=prec) * scale
        mask = None
        if causal:
            q_pos = qb * np.int32(block_q) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * np.int32(block_k) + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = q_pos + np.int32(causal_offset) >= k_pos
            if window is not None:
                mask &= k_pos > q_pos + np.int32(causal_offset - window)
            s_m = jnp.where(mask, s, -1e30)
        else:
            s_m = s
        if with_kmask:
            s_m = jnp.where(kmask_ref[...][:1] > 0, s_m, -1e30)
        p = jnp.exp(s_m - lse)                   # (block_q, block_k)
        if causal:
            p = jnp.where(mask, p, 0.0)
        if with_kmask:
            p = jnp.where(kmask_ref[...][:1] > 0, p, 0.0)
        p_op = p.astype(jnp.bfloat16) if lowp else p
        dv_scr[...] += jnp.dot(p_op.T, g,
                               preferred_element_type=jnp.float32,
                               precision=prec)
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32,
                     precision=prec)
        ds = p * (dp - delta.astype(jnp.float32))
        ds_op = ds.astype(jnp.bfloat16) if lowp else ds
        dk_scr[...] += jnp.dot(ds_op.T, q,
                               preferred_element_type=jnp.float32,
                               precision=prec) * scale

    if causal:
        # skip q-blocks that cannot attend this k-block: fully-masked
        # key columns keep their exact-zero dK/dV from the scratch
        # init (p is hard-zeroed in the old path, so this is exact for
        # any causal_offset)
        visible = (qb * np.int32(block_q)
                   + np.int32(block_q - 1 + causal_offset)
                   >= kb * np.int32(block_k))
        if window is not None:
            # band floor: this k-block is past every window when its
            # last key <= the q-block's first query's floor
            visible &= (kb * np.int32(block_k) + np.int32(block_k - 1)
                        > qb * np.int32(block_q)
                        + np.int32(causal_offset - window))
        pl.when(visible)(_accum)
    else:
        _accum()

    @pl.when(qb == num_q_blocks - 1)
    def _done():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, g, scale, causal,
                      kmask=None, window=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s_q, h, d_orig = q.shape
    s_k = k.shape[1]
    pad = (-d_orig) % _LANE
    if pad:
        widths = ((0, 0), (0, 0), (0, 0), (0, pad))
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
        out = jnp.pad(out, widths)
        g = jnp.pad(g, widths)
    d = d_orig + pad
    qf = _fold(q, b, h, s_q, d)
    kf = _fold(k, b, h, s_k, d)
    vf = _fold(v, b, h, s_k, d)
    gf = _fold(g, b, h, s_q, d)
    of = _fold(out, b, h, s_q, d)
    # Δ = rowsum(dO ∘ O), lane-replicated like the saved LSE
    delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)
    delta = jnp.broadcast_to(delta, (b * h, s_q, _LANE))

    bq, bk = _blocks(s_q, s_k)
    num_q_blocks = s_q // bq
    num_k_blocks = s_k // bk
    causal_offset = s_k - s_q
    zero, q_spec, k_spec = _blocked_specs(d, bq, bk)
    lseq_spec = pl.BlockSpec((None, bq, _LANE),
                             lambda i, j, kb: (i, j, zero(i)))

    dq_in_specs = [q_spec, k_spec, k_spec, q_spec, lseq_spec,
                   lseq_spec]
    dq_inputs = [qf, kf, vf, gf, lse, delta]
    if kmask is not None:
        dq_in_specs.append(_kmask_spec(h, bk=bk))
        dq_inputs.append(_kmask_rows(kmask, s_k))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          num_k_blocks=num_k_blocks,
                          causal_offset=causal_offset,
                          with_kmask=kmask is not None,
                          window=window),
        grid=(b * h, num_q_blocks, num_k_blocks),
        in_specs=dq_in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_INTERPRET,
    )(*dq_inputs)

    # pass 2: grid is (BH, k-block, q-block) — index maps swap roles
    kk_spec = pl.BlockSpec((None, bk, d),
                           lambda i, kb, j: (i, kb, zero(i)))
    qq_spec = pl.BlockSpec((None, bq, d),
                           lambda i, kb, j: (i, j, zero(i)))
    lse2_spec = pl.BlockSpec((None, bq, _LANE),
                             lambda i, kb, j: (i, j, zero(i)))
    dkv_in_specs = [kk_spec, kk_spec, qq_spec, qq_spec, lse2_spec,
                    lse2_spec]
    dkv_inputs = [kf, vf, qf, gf, lse, delta]
    if kmask is not None:
        # grid here is (BH, k-block, q-block): mask block follows kb
        dkv_in_specs.append(_kmask_spec(h, kb_in_dim2=False, bk=bk))
        dkv_inputs.append(_kmask_rows(kmask, s_k))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          num_q_blocks=num_q_blocks,
                          causal_offset=causal_offset,
                          with_kmask=kmask is not None,
                          window=window),
        grid=(b * h, num_k_blocks, num_q_blocks),
        in_specs=dkv_in_specs,
        out_specs=[kk_spec, kk_spec],
        out_shape=[jax.ShapeDtypeStruct((b * h, s_k, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, s_k, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=_INTERPRET,
    )(*dkv_inputs)

    dq = _unfold(dq, b, h, s_q, d)[..., :d_orig]
    dk = _unfold(dk, b, h, s_k, d)[..., :d_orig]
    dv = _unfold(dv, b, h, s_k, d)[..., :d_orig]
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, kmask, scale, causal, window):
    # primal (inference) path: no LSE output at all
    out, _ = _flash_fwd_pallas(q, k, v, scale, causal, want_lse=False,
                               kmask=kmask, window=window)
    return out


def _flash_fwd(q, k, v, kmask, scale, causal, window):
    out, lse = _flash_fwd_pallas(q, k, v, scale, causal, kmask=kmask,
                                 window=window)
    # residual holds ONE lane of the lane-replicated LSE: the full
    # (BH, S, 128) copy would cost 128x the HBM across the fwd→bwd
    # interval on exactly the long-context runs flash exists for
    return out, (q, k, v, out, lse[:, :, :1], kmask)


def _flash_bwd(scale, causal, window, res, g):
    q, k, v, out, lse1, kmask = res
    lse = jnp.broadcast_to(lse1, lse1.shape[:2] + (_LANE,))
    dq, dk, dv = _flash_bwd_pallas(q, k, v, out, lse, g, scale, causal,
                                   kmask=kmask, window=window)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def _as_key_padding(mask, batch=None, s_k=None, s_q=None):
    """(B, 1, 1, S_k) / (B, S_k) masks depend only on key position —
    the flash kernels support those; anything query- or head-dependent
    (incl. 2-D (S_q, S_k) attention masks) returns None (XLA
    fallback).  The result is broadcast to ``batch`` rows so the
    per-batch kernel block indexing is always in range.

    A 2-D mask whose shape satisfies BOTH readings — (B, S_k) key
    padding and (S_q, S_k) attention matrix, i.e. B == S_q — is
    genuinely ambiguous, and either silent binding corrupts numerics
    for the other intent, so it raises (ADVICE r2): disambiguate with
    ``kmask=`` / a (B, 1, 1, S_k) reshape for key padding, or a
    (1, 1, S_q, S_k) reshape for attention-matrix semantics."""
    import jax.numpy as _jnp

    if mask is None:
        return None
    km = None
    if mask.ndim == 2:
        # the documented 2-D form is per-batch key padding: accept
        # exactly (B, S_k); other 2-D shapes keep the legacy XLA
        # broadcast behavior
        if batch is not None and s_k is not None and \
                mask.shape == (batch, s_k):
            if s_q is not None and batch == s_q and batch > 1:
                from ..base import MXNetError
                raise MXNetError(
                    f"ambiguous 2-D attention mask {mask.shape}: with "
                    f"batch == S_q == {batch} it reads equally as "
                    "(B, S_k) key padding or an (S_q, S_k) attention "
                    "matrix. Pass kmask=/reshape((B, 1, 1, S_k)) for "
                    "key padding, or reshape((1, 1, S_q, S_k)) for "
                    "attention-matrix semantics.")
            km = mask
    elif mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1:
        km = mask.reshape(mask.shape[0], mask.shape[3])
    if km is None:
        return None
    if batch is not None and km.shape[0] == 1 and batch > 1:
        km = _jnp.broadcast_to(km, (batch,) + km.shape[1:])
    if batch is not None and km.shape[0] != batch:
        return None
    return km


def flash_attention(q, k, v, mask=None, scale=None, causal=False,
                    kmask=None, window=None):
    """Flash attention; (B, S, H, D) in/out.

    Key-padding masks ((B, 1, 1, S_k) or (B, S_k)) run INSIDE the
    kernels (fwd and both bwd passes); general query-dependent masks
    fall back to the XLA path.  Dispatchers that already normalized the
    mask pass ``kmask`` directly (avoids a second conversion).

    ``window``: sliding-window (banded causal, Mistral-style) width —
    query i attends keys (i+off-W, i+off].  Requires ``causal=True``.
    The kernels SKIP out-of-band blocks, so compute is O(S·W) instead
    of O(S²) — the long-context shape ring attention composes with."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if window is not None:
        window = int(window)
        if not causal:
            from ..base import MXNetError
            raise MXNetError(
                "flash_attention: window= requires causal=True "
                "(sliding window is a banded CAUSAL mask)")
        if window <= 0:
            from ..base import MXNetError
            raise MXNetError(f"flash_attention: window must be "
                             f"positive, got {window}")
        if window >= k.shape[1]:
            window = None             # band wider than keys = causal
    if kmask is None and mask is not None:
        kmask = _as_key_padding(mask, batch=q.shape[0], s_k=k.shape[1],
                                s_q=q.shape[1])
        if kmask is None:
            # query-dependent masks: XLA broadcast path, exactly the
            # pre-kernel behavior (ambiguous B==S_q 2-D masks raise
            # inside _as_key_padding instead)
            from .attention import _sdpa_xla
            return _sdpa_xla(q, k, v, mask, scale, causal,
                             window=window)
    return _flash(q, k, v, kmask, float(scale), bool(causal), window)
