"""Pallas flash-attention kernel for TPU.

Capability parity / perf: the reference leans on cuDNN fused attention
(contrib transformer ops); the TPU equivalent is a Pallas kernel that
streams K/V blocks through VMEM with an online-softmax accumulator, never
materializing the (S,S) score matrix in HBM (SURVEY.md §5 "Long-context",
pallas_guide.md tiling/grid sections).

Forward is the Pallas kernel; backward recomputes attention with the XLA
path under ``jax.custom_vjp`` (flash-bwd kernel is a later milestone —
recompute costs one extra forward but keeps memory O(S) instead of O(S²)
on the forward pass, which is where long-context runs die).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["flash_attention"]

_BLOCK_Q = 128
_BLOCK_K = 128
_LANE = 128  # TPU lane width: head_dim is zero-padded up to this

# interpret mode runs the kernel on the Pallas interpreter (any backend)
# — used by the CPU test suite; toggled via tests or MXTPU_FLASH_INTERPRET
_INTERPRET = bool(os.environ.get("MXTPU_FLASH_INTERPRET"))


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                scale, causal, num_k_blocks, causal_offset):
    """One (batch*head, q-block, k-block) grid step.

    The k-block loop lives in the GRID (innermost dim, sequential on TPU)
    with the online-softmax state in VMEM scratch persisting across
    steps — the canonical Pallas flash layout, and it keeps every index
    static (dynamic in-kernel slices mis-lower under jax_enable_x64).
    """
    from jax.experimental import pallas as pl

    q_idx = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    q = q_ref[...]  # (block_q, d)
    k = k_ref[...]  # (block_k, d)
    v = v_ref[...]
    block_q, d = q.shape
    block_k = k.shape[0]

    s = jnp.dot(q.astype(jnp.float32), k.astype(jnp.float32).T,
                preferred_element_type=jnp.float32) * scale
    if causal:
        # end-aligned like the XLA oracle's tril(k=s_k-s_q): query i may
        # attend keys up to i + (s_k - s_q), so cross-attention with
        # s_k != s_q masks identically on both paths
        q_pos = q_idx * np.int32(block_q) + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = kb * np.int32(block_k) + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos + np.int32(causal_offset) >= k_pos, s, -1e30)

    # m/l scratch is (block_q, 128): TPU vector stores need a full lane
    # dim; value is replicated across lanes, column 0 is authoritative
    m = m_scr[...][:, :1]
    l = l_scr[...][:, :1]
    acc = acc_scr[...]
    m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    lanes = m_scr.shape[1]
    m_scr[...] = jnp.broadcast_to(m_new, (m_new.shape[0], lanes))
    l_new = alpha * l + p.sum(axis=1, keepdims=True)
    l_scr[...] = jnp.broadcast_to(l_new, (l_new.shape[0], lanes))
    acc_scr[...] = alpha * acc + jnp.dot(
        p, v.astype(jnp.float32), preferred_element_type=jnp.float32)

    @pl.when(kb == num_k_blocks - 1)
    def _done():
        o_ref[...] = (acc_scr[...] / l_scr[...][:, :1]).astype(
            o_ref.dtype)


def _flash_fwd_pallas(q, k, v, scale, causal):
    """q,k,v: (B, S, H, D) → out (B, S, H, D).

    head_dim < 128 (e.g. BERT's 64) is zero-padded up to the lane
    width: QKᵀ contracts over D so zero columns don't change scores,
    and PV leaves the padded output columns zero — sliced off at the
    end.  XLA would pad the minor dim to 128 on the MXU anyway, so the
    padding costs ~nothing on chip.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s_q, h, d_orig = q.shape
    s_k = k.shape[1]
    pad = (-d_orig) % _LANE
    if pad:
        widths = ((0, 0), (0, 0), (0, 0), (0, pad))
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    d = d_orig + pad
    # fold batch×head, make seq-major: (B*H, S, D)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, s_q, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, s_k, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, s_k, d)

    num_k_blocks = s_k // _BLOCK_K
    grid = (b * h, s_q // _BLOCK_Q, num_k_blocks)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               num_k_blocks=num_k_blocks,
                               causal_offset=s_k - s_q)
    # NOTE on index maps: with jax_enable_x64 a literal `0` in an index
    # map becomes i64 and Mosaic rejects the mixed (i32, i64) signature;
    # `i - i` keeps everything i32 regardless of the x64 flag.
    zero = lambda i: i - i
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, _BLOCK_Q, d),
                         lambda i, j, kb: (i, j, zero(i))),
            pl.BlockSpec((None, _BLOCK_K, d),
                         lambda i, j, kb: (i, kb, zero(i))),
            pl.BlockSpec((None, _BLOCK_K, d),
                         lambda i, j, kb: (i, kb, zero(i))),
        ],
        out_specs=pl.BlockSpec((None, _BLOCK_Q, d),
                               lambda i, j, kb: (i, j, zero(i))),
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((_BLOCK_Q, 128), jnp.float32),
            pltpu.VMEM((_BLOCK_Q, 128), jnp.float32),
            pltpu.VMEM((_BLOCK_Q, d), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(qf, kf, vf)
    out = out.reshape(b, h, s_q, d).transpose(0, 2, 1, 3)
    if pad:
        out = out[..., :d_orig]
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, mask, scale, causal):
    return _flash_fwd_pallas(q, k, v, scale, causal)


def _flash_fwd(q, k, v, mask, scale, causal):
    return _flash_fwd_pallas(q, k, v, scale, causal), (q, k, v, mask)


def _flash_bwd(scale, causal, res, g):
    # recompute with the XLA path; its vjp gives exact gradients
    q, k, v, mask = res
    from .attention import _sdpa_xla

    def f(q, k, v):
        return _sdpa_xla(q, k, v, mask, scale, causal)

    _, vjp = jax.vjp(f, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, mask=None, scale=None, causal=False):
    """Flash attention; (B, S, H, D) in/out.  Mask is handled by the XLA
    fallback path (masked flash lands with the long-context milestone) —
    callers pass mask=None on the flash path."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if mask is not None:
        from .attention import _sdpa_xla
        return _sdpa_xla(q, k, v, mask, scale, causal)
    return _flash(q, k, v, None, float(scale), bool(causal))
