"""Detection / bounding-box ops.

Capability parity: reference ``src/operator/contrib/`` detection family
(``roi_align.cc``, ``bounding_box.cc`` with ``box_iou``/``box_nms`` —
SURVEY.md §2.2 "Sequence/attention-adjacent ops" row, used by GluonCV).

TPU-first notes: everything is static-shape.  ``box_nms`` keeps the
MXNet contract — output has the SAME shape as the input with suppressed
rows' entries set to -1 — which maps cleanly onto a fixed-trip
``lax.fori_loop`` (greedy suppression over score-sorted boxes) instead
of the reference's dynamic-length CUDA kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, alias


def _iou_corner(lhs, rhs):
    """IoU between (..., N, 4) and (..., M, 4) corner boxes → (..., N, M)."""
    lx1, ly1, lx2, ly2 = [lhs[..., :, None, i] for i in range(4)]
    rx1, ry1, rx2, ry2 = [rhs[..., None, :, i] for i in range(4)]
    ix = jnp.clip(jnp.minimum(lx2, rx2) - jnp.maximum(lx1, rx1), 0, None)
    iy = jnp.clip(jnp.minimum(ly2, ry2) - jnp.maximum(ly1, ry1), 0, None)
    inter = ix * iy
    area_l = jnp.clip(lx2 - lx1, 0, None) * jnp.clip(ly2 - ly1, 0, None)
    area_r = jnp.clip(rx2 - rx1, 0, None) * jnp.clip(ry2 - ry1, 0, None)
    union = area_l + area_r - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _to_corner(boxes):
    """center (x, y, w, h) → corner (x1, y1, x2, y2)."""
    x, y, w, h = [boxes[..., i] for i in range(4)]
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2],
                     axis=-1)


@register("_contrib_box_iou", num_inputs=2)
def box_iou(lhs, rhs, *, format="corner"):
    """Pairwise IoU (parity: mx.nd.contrib.box_iou)."""
    if format == "center":
        lhs, rhs = _to_corner(lhs), _to_corner(rhs)
    return _iou_corner(lhs, rhs)


@register("_contrib_box_nms", num_inputs=1)
def box_nms(data, *, overlap_thresh=0.5, valid_thresh=0, topk=-1,
            coord_start=2, score_index=1, id_index=-1,
            force_suppress=False, in_format="corner",
            out_format="corner"):
    """Greedy non-maximum suppression (parity: mx.nd.contrib.box_nms).

    data: (..., N, K) — per-box rows with a score at ``score_index``,
    coords at ``coord_start:coord_start+4``, optional class id at
    ``id_index``.  Suppressed/invalid rows come back as all -1, rows are
    sorted by descending score (the reference's default behaviour).
    """
    if in_format == "center" or out_format == "center":
        raise NotImplementedError(
            "box_nms: center format not implemented (corner only)")

    def nms_single(d):
        n = d.shape[0]
        scores = d[:, score_index]
        valid = scores > valid_thresh
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        d_sorted = d[order]
        valid_sorted = valid[order]
        if topk > 0:
            keep_rank = jnp.arange(n) < topk
            valid_sorted = valid_sorted & keep_rank
        boxes = jax.lax.dynamic_slice_in_dim(d_sorted, coord_start, 4,
                                             axis=1)
        iou = _iou_corner(boxes, boxes)
        if id_index >= 0 and not force_suppress:
            ids = d_sorted[:, id_index]
            same_class = ids[:, None] == ids[None, :]
            iou = jnp.where(same_class, iou, 0.0)

        def body(i, keep):
            # suppress j > i overlapping i, iff i itself is kept
            sup = (iou[i] > overlap_thresh) & (jnp.arange(n) > i) \
                & keep[i]
            return keep & ~sup

        keep = jax.lax.fori_loop(0, n, body, valid_sorted)
        return jnp.where(keep[:, None], d_sorted, -1.0)

    batch_shape = data.shape[:-2]
    flat = data.reshape((-1,) + data.shape[-2:])
    out = jax.vmap(nms_single)(flat)
    return out.reshape(batch_shape + data.shape[-2:])


@register("_contrib_ROIAlign", num_inputs=2)
def roi_align(data, rois, *, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False):
    """ROI Align with bilinear sampling (parity:
    mx.nd.contrib.ROIAlign; Mask R-CNN's pooling).

    data: (N, C, H, W); rois: (R, 5) rows [batch_idx, x1, y1, x2, y2]
    in image coordinates.  Returns (R, C, PH, PW).
    """
    if position_sensitive:
        raise NotImplementedError("position_sensitive ROIAlign")
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))
    n, c, h, w = data.shape
    sr = sample_ratio if sample_ratio > 0 else 2

    def one_roi(roi):
        bidx = roi[0].astype("int32")
        x1, y1, x2, y2 = [roi[i + 1] * spatial_scale for i in range(4)]
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w, bin_h = rw / pw, rh / ph
        # sample grid: (ph*sr, pw*sr) bilinear taps, mean-pooled per bin
        ys = y1 + (jnp.arange(ph * sr) + 0.5) * (bin_h / sr)
        xs = x1 + (jnp.arange(pw * sr) + 0.5) * (bin_w / sr)
        img = data[bidx]                                   # (C, H, W)

        y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype("int32")
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype("int32")
        wy = jnp.clip(ys - y0, 0.0, 1.0)
        wx = jnp.clip(xs - x0, 0.0, 1.0)
        y0 = y0.astype("int32")
        x0 = x0.astype("int32")

        def gather(yi, xi):
            return img[:, yi, :][:, :, xi]                 # (C, Sy, Sx)

        v = (gather(y0, x0) * ((1 - wy)[:, None] * (1 - wx)[None, :])
             + gather(y0, x1i) * ((1 - wy)[:, None] * wx[None, :])
             + gather(y1i, x0) * (wy[:, None] * (1 - wx)[None, :])
             + gather(y1i, x1i) * (wy[:, None] * wx[None, :]))
        # mean over each bin's sr x sr taps
        v = v.reshape((c, ph, sr, pw, sr))
        return v.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois.astype(data.dtype))


alias("box_iou", "_contrib_box_iou")
alias("box_nms", "_contrib_box_nms")
alias("ROIAlign", "_contrib_ROIAlign")
