"""Detection / bounding-box ops.

Capability parity: reference ``src/operator/contrib/`` detection family
(``roi_align.cc``, ``bounding_box.cc`` with ``box_iou``/``box_nms`` —
SURVEY.md §2.2 "Sequence/attention-adjacent ops" row, used by GluonCV).

TPU-first notes: everything is static-shape.  ``box_nms`` keeps the
MXNet contract — output has the SAME shape as the input with suppressed
rows' entries set to -1 — which maps cleanly onto a fixed-trip
``lax.fori_loop`` (greedy suppression over score-sorted boxes) instead
of the reference's dynamic-length CUDA kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, alias


def _iou_corner(lhs, rhs):
    """IoU between (..., N, 4) and (..., M, 4) corner boxes → (..., N, M)."""
    lx1, ly1, lx2, ly2 = [lhs[..., :, None, i] for i in range(4)]
    rx1, ry1, rx2, ry2 = [rhs[..., None, :, i] for i in range(4)]
    ix = jnp.clip(jnp.minimum(lx2, rx2) - jnp.maximum(lx1, rx1), 0, None)
    iy = jnp.clip(jnp.minimum(ly2, ry2) - jnp.maximum(ly1, ry1), 0, None)
    inter = ix * iy
    area_l = jnp.clip(lx2 - lx1, 0, None) * jnp.clip(ly2 - ly1, 0, None)
    area_r = jnp.clip(rx2 - rx1, 0, None) * jnp.clip(ry2 - ry1, 0, None)
    union = area_l + area_r - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _to_corner(boxes):
    """center (x, y, w, h) → corner (x1, y1, x2, y2)."""
    x, y, w, h = [boxes[..., i] for i in range(4)]
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2],
                     axis=-1)


@register("_contrib_box_iou", num_inputs=2)
def box_iou(lhs, rhs, *, format="corner"):
    """Pairwise IoU (parity: mx.nd.contrib.box_iou)."""
    if format == "center":
        lhs, rhs = _to_corner(lhs), _to_corner(rhs)
    return _iou_corner(lhs, rhs)


@register("_contrib_box_nms", num_inputs=1)
def box_nms(data, *, overlap_thresh=0.5, valid_thresh=0, topk=-1,
            coord_start=2, score_index=1, id_index=-1,
            force_suppress=False, in_format="corner",
            out_format="corner"):
    """Greedy non-maximum suppression (parity: mx.nd.contrib.box_nms).

    data: (..., N, K) — per-box rows with a score at ``score_index``,
    coords at ``coord_start:coord_start+4``, optional class id at
    ``id_index``.  Suppressed/invalid rows come back as all -1, rows are
    sorted by descending score (the reference's default behaviour).
    """
    if in_format == "center" or out_format == "center":
        raise NotImplementedError(
            "box_nms: center format not implemented (corner only)")

    def nms_single(d):
        n = d.shape[0]
        scores = d[:, score_index]
        valid = scores > valid_thresh
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        d_sorted = d[order]
        valid_sorted = valid[order]
        if topk > 0:
            keep_rank = jnp.arange(n) < topk
            valid_sorted = valid_sorted & keep_rank
        boxes = jax.lax.dynamic_slice_in_dim(d_sorted, coord_start, 4,
                                             axis=1)
        iou = _iou_corner(boxes, boxes)
        if id_index >= 0 and not force_suppress:
            ids = d_sorted[:, id_index]
            same_class = ids[:, None] == ids[None, :]
            iou = jnp.where(same_class, iou, 0.0)

        def body(i, keep):
            # suppress j > i overlapping i, iff i itself is kept
            sup = (iou[i] > overlap_thresh) & (jnp.arange(n) > i) \
                & keep[i]
            return keep & ~sup

        keep = jax.lax.fori_loop(0, n, body, valid_sorted)
        return jnp.where(keep[:, None], d_sorted, -1.0)

    batch_shape = data.shape[:-2]
    flat = data.reshape((-1,) + data.shape[-2:])
    out = jax.vmap(nms_single)(flat)
    return out.reshape(batch_shape + data.shape[-2:])


@register("_contrib_ROIAlign", num_inputs=2)
def roi_align(data, rois, *, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False):
    """ROI Align with bilinear sampling (parity:
    mx.nd.contrib.ROIAlign; Mask R-CNN's pooling).

    data: (N, C, H, W); rois: (R, 5) rows [batch_idx, x1, y1, x2, y2]
    in image coordinates.  Returns (R, C, PH, PW).
    """
    if position_sensitive:
        raise NotImplementedError("position_sensitive ROIAlign")
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))
    n, c, h, w = data.shape
    sr = sample_ratio if sample_ratio > 0 else 2

    def one_roi(roi):
        bidx = roi[0].astype("int32")
        x1, y1, x2, y2 = [roi[i + 1] * spatial_scale for i in range(4)]
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w, bin_h = rw / pw, rh / ph
        # sample grid: (ph*sr, pw*sr) bilinear taps, mean-pooled per bin
        ys = y1 + (jnp.arange(ph * sr) + 0.5) * (bin_h / sr)
        xs = x1 + (jnp.arange(pw * sr) + 0.5) * (bin_w / sr)
        img = data[bidx]                                   # (C, H, W)

        y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype("int32")
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype("int32")
        wy = jnp.clip(ys - y0, 0.0, 1.0)
        wx = jnp.clip(xs - x0, 0.0, 1.0)
        y0 = y0.astype("int32")
        x0 = x0.astype("int32")

        def gather(yi, xi):
            return img[:, yi, :][:, :, xi]                 # (C, Sy, Sx)

        v = (gather(y0, x0) * ((1 - wy)[:, None] * (1 - wx)[None, :])
             + gather(y0, x1i) * ((1 - wy)[:, None] * wx[None, :])
             + gather(y1i, x0) * (wy[:, None] * (1 - wx)[None, :])
             + gather(y1i, x1i) * (wy[:, None] * wx[None, :]))
        # mean over each bin's sr x sr taps
        v = v.reshape((c, ph, sr, pw, sr))
        return v.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois.astype(data.dtype))


alias("box_iou", "_contrib_box_iou")
alias("box_nms", "_contrib_box_nms")
alias("ROIAlign", "_contrib_ROIAlign")


# ---------------------------------------------------------------------------
# legacy SSD ops — reference src/operator/contrib/multibox_{prior,target,
# detection}.cc (the example/ssd training/inference path)
# ---------------------------------------------------------------------------


@register("_contrib_MultiBoxPrior", num_inputs=1)
def multibox_prior(data, *, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor generation (parity: multibox_prior.cc).

    data: (B, C, H, W) feature map (values unused — only H, W matter).
    Per pixel: ``len(sizes) + len(ratios) - 1`` anchors — every size at
    ratios[0], plus sizes[0] at each remaining ratio.  Returns
    (1, H*W*A, 4) corner boxes in normalized [0, 1] coordinates.
    """
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    half = []
    r0 = float(np.sqrt(ratios[0]))
    for s in sizes:
        half.append((s * r0 / 2.0, s / r0 / 2.0))
    for r in ratios[1:]:
        sr = float(np.sqrt(r))
        half.append((sizes[0] * sr / 2.0, sizes[0] / sr / 2.0))
    hw = jnp.asarray([p[0] for p in half], jnp.float32)  # (A,)
    hh = jnp.asarray([p[1] for p in half], jnp.float32)
    cxg = cx[None, :, None]
    cyg = cy[:, None, None]
    boxes = jnp.stack(
        [jnp.broadcast_to(cxg - hw, (h, w, hw.size)),
         jnp.broadcast_to(cyg - hh, (h, w, hw.size)),
         jnp.broadcast_to(cxg + hw, (h, w, hw.size)),
         jnp.broadcast_to(cyg + hh, (h, w, hw.size))], axis=-1)
    boxes = boxes.reshape(1, h * w * hw.size, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


def _encode_loc(anchors, gt, variances):
    """Corner anchors + corner GT → (dx, dy, dw, dh) regression target."""
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = (anchors[..., 0] + anchors[..., 2]) / 2
    ay = (anchors[..., 1] + anchors[..., 3]) / 2
    gw = jnp.clip(gt[..., 2] - gt[..., 0], 1e-8, None)
    gh = jnp.clip(gt[..., 3] - gt[..., 1], 1e-8, None)
    gx = (gt[..., 0] + gt[..., 2]) / 2
    gy = (gt[..., 1] + gt[..., 3]) / 2
    dx = (gx - ax) / jnp.clip(aw, 1e-8, None) / variances[0]
    dy = (gy - ay) / jnp.clip(ah, 1e-8, None) / variances[1]
    dw = jnp.log(gw / jnp.clip(aw, 1e-8, None)) / variances[2]
    dh = jnp.log(gh / jnp.clip(ah, 1e-8, None)) / variances[3]
    return jnp.stack([dx, dy, dw, dh], axis=-1)


@register("_contrib_MultiBoxTarget", num_inputs=3, num_outputs=3)
def multibox_target(anchors, labels, cls_preds, *,
                    overlap_threshold=0.5, ignore_label=-1.0,
                    negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Anchor→GT matching + target encoding (multibox_target.cc).

    anchors (1, N, 4) corner; labels (B, M, 5) rows [cls, x1, y1, x2,
    y2] padded with cls=-1; cls_preds (B, C+1, N) (used only for hard
    negative mining, which is structurally supported via the
    ``negative_mining_ratio`` contract).
    Returns loc_target (B, N*4), loc_mask (B, N*4), cls_target (B, N) —
    cls_target is shifted by +1 (0 = background), the reference layout.
    """
    anc = anchors[0]  # (N, 4)
    n = anc.shape[0]

    def one(sample_labels, sample_cls_preds):
        cls = sample_labels[:, 0]
        valid = cls >= 0  # (M,)
        gt = sample_labels[:, 1:5]
        iou = _iou_corner(anc, gt)          # (N, M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_iou = iou.max(axis=1)          # per anchor
        best_gt = iou.argmax(axis=1)
        pos = best_iou >= overlap_threshold
        # bipartite half: every valid GT claims its best anchor.
        # Padding rows (cls<0) are routed to index n, which mode="drop"
        # discards — otherwise their argmax lands on anchor 0 and can
        # cancel a valid GT's claim there.
        gt_best_anchor = jnp.where(valid, iou.argmax(axis=0), n)  # (M,)
        forced = jnp.zeros((n,), bool).at[gt_best_anchor].set(
            True, mode="drop")
        claimed_gt = jnp.zeros((n,), jnp.int32).at[gt_best_anchor].set(
            jnp.arange(gt.shape[0], dtype=jnp.int32), mode="drop")
        match = jnp.where(forced, claimed_gt, best_gt)
        pos = pos | forced
        matched_gt = gt[match]              # (N, 4)
        loc_t = _encode_loc(anc, matched_gt, variances)
        loc_t = jnp.where(pos[:, None], loc_t, 0.0).reshape(-1)
        loc_m = jnp.where(pos[:, None],
                          jnp.ones((n, 4), jnp.float32),
                          0.0).reshape(-1)
        cls_t = jnp.where(pos, cls[match] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # hard negative mining: keep the highest-background-loss
            # negatives up to ratio * num_pos; rest -> ignore_label
            bg_logit = sample_cls_preds[0]  # (N,)
            max_logit = sample_cls_preds.max(axis=0)
            neg_score = max_logit - bg_logit  # high = confident non-bg
            # near-positives (IoU >= mining thresh) are excluded from
            # mining, per the reference multibox_target.cc contract
            neg_score = jnp.where(
                best_iou < negative_mining_thresh, neg_score, -jnp.inf)
            num_pos = pos.sum()
            quota = (negative_mining_ratio * num_pos).astype(jnp.int32)
            quota = jnp.maximum(quota, minimum_negative_samples)
            neg_rank = jnp.argsort(
                jnp.argsort(-jnp.where(pos, -jnp.inf, neg_score)))
            # near-positives carry -inf score but still occupy ranks;
            # when the quota exceeds the true-negative count they must
            # land on ignore_label, not background (ADVICE r2)
            keep_neg = (~pos) & (best_iou < negative_mining_thresh) \
                & (neg_rank < quota)
            cls_t = jnp.where(pos | keep_neg, cls_t, ignore_label)
        return loc_t, loc_m, cls_t

    loc_t, loc_m, cls_t = jax.vmap(one)(labels, cls_preds)
    return loc_t, loc_m, cls_t


@register("_contrib_MultiBoxDetection", num_inputs=3)
def multibox_detection(cls_probs, loc_preds, anchors, *, clip=True,
                       threshold=0.01, background_id=0,
                       nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode + per-class NMS (multibox_detection.cc).

    cls_probs (B, C+1, N), loc_preds (B, N*4), anchors (1, N, 4) →
    (B, N, 6) rows [cls_id, score, x1, y1, x2, y2]; suppressed rows -1.
    """
    anc = anchors[0]
    aw = anc[:, 2] - anc[:, 0]
    ah = anc[:, 3] - anc[:, 1]
    ax = (anc[:, 0] + anc[:, 2]) / 2
    ay = (anc[:, 1] + anc[:, 3]) / 2

    def one(probs, locs):
        d = locs.reshape(-1, 4)
        cx = d[:, 0] * variances[0] * aw + ax
        cy = d[:, 1] * variances[1] * ah + ay
        w = jnp.exp(jnp.clip(d[:, 2] * variances[2], None, 10.0)) * aw
        h = jnp.exp(jnp.clip(d[:, 3] * variances[3], None, 10.0)) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2, cy + h / 2], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor (reference decode rule)
        fg = jnp.concatenate(
            [probs[:background_id], probs[background_id + 1:]], axis=0)
        # ids are renumbered foreground classes (background row removed),
        # the reference's output convention
        cls_id = fg.argmax(axis=0).astype(jnp.float32)
        score = fg.max(axis=0)
        keep = score > threshold
        rows = jnp.concatenate(
            [jnp.where(keep, cls_id, -1.0)[:, None],
             jnp.where(keep, score, -1.0)[:, None], boxes], axis=1)
        return box_nms(rows, overlap_thresh=nms_threshold,
                       valid_thresh=0.0, topk=nms_topk, coord_start=2,
                       score_index=1, id_index=0,
                       force_suppress=force_suppress)

    return jax.vmap(one)(cls_probs, loc_preds)


@register("_contrib_box_encode", num_inputs=4, num_outputs=2)
def box_encode(samples, matches, anchors, refs, *,
               means=(0.0, 0.0, 0.0, 0.0),
               stds=(0.1, 0.1, 0.2, 0.2)):
    """Anchor-box regression targets (parity:
    ``mx.nd.contrib.box_encode``; reference
    ``src/operator/contrib/bounding_box.cc``).

    samples (B, N) ∈ {1 pos, 0/-1 ignore}; matches (B, N) gt index per
    anchor; anchors (B, N, 4) and refs (B, M, 4) in corner format.
    Returns (targets (B, N, 4), masks (B, N, 4)).
    """
    a_w = anchors[:, :, 2] - anchors[:, :, 0]
    a_h = anchors[:, :, 3] - anchors[:, :, 1]
    a_x = anchors[:, :, 0] + a_w * 0.5
    a_y = anchors[:, :, 1] + a_h * 0.5
    m = matches.astype(jnp.int32)
    g = jnp.take_along_axis(refs, m[:, :, None].clip(0), axis=1)
    g_w = g[:, :, 2] - g[:, :, 0]
    g_h = g[:, :, 3] - g[:, :, 1]
    g_x = g[:, :, 0] + g_w * 0.5
    g_y = g[:, :, 1] + g_h * 0.5
    eps = 1e-8
    t = jnp.stack([
        ((g_x - a_x) / (a_w + eps) - means[0]) / stds[0],
        ((g_y - a_y) / (a_h + eps) - means[1]) / stds[1],
        (jnp.log(jnp.maximum(g_w, eps) / (a_w + eps)) - means[2])
        / stds[2],
        (jnp.log(jnp.maximum(g_h, eps) / (a_h + eps)) - means[3])
        / stds[3]], axis=-1)
    mask = jnp.broadcast_to((samples > 0.5)[:, :, None],
                            t.shape).astype(t.dtype)
    return t * mask, mask


@register("_contrib_box_decode", num_inputs=2)
def box_decode(data, anchors, *, std0=0.1, std1=0.1, std2=0.2,
               std3=0.2, clip=-1.0, format="corner"):
    """Regression deltas → boxes (parity: ``mx.nd.contrib.box_decode``).

    data (B, N, 4) deltas; anchors (1|B, N, 4).  Output corner boxes.
    """
    if format == "center":
        a_x, a_y = anchors[..., 0], anchors[..., 1]
        a_w, a_h = anchors[..., 2], anchors[..., 3]
    else:
        a_w = anchors[..., 2] - anchors[..., 0]
        a_h = anchors[..., 3] - anchors[..., 1]
        a_x = anchors[..., 0] + a_w * 0.5
        a_y = anchors[..., 1] + a_h * 0.5
    x = data[..., 0] * std0 * a_w + a_x
    y = data[..., 1] * std1 * a_h + a_y
    # reference clip bounds the SCALED log-deltas before exp (a growth
    # cap like GluonCV's clip≈6.586), not the output coords — and ONLY
    # when clip > 0: the default -1 means "no clip", so extreme deltas
    # must decode unclamped exactly as the reference op does (ADVICE r3)
    dw = data[..., 2] * std2
    dh = data[..., 3] * std3
    if clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    w = jnp.exp(dw) * a_w * 0.5
    h = jnp.exp(dh) * a_h * 0.5
    return jnp.stack([x - w, y - h, x + w, y + h], axis=-1)


@register("_contrib_bipartite_matching", num_inputs=1, num_outputs=2)
def bipartite_matching(dist, *, is_ascend=False, threshold=0.5,
                       topk=-1):
    """Greedy bipartite matching over a (B, N, M) score matrix
    (parity: ``mx.nd.contrib.bipartite_matching``; used by detection
    target assignment).  Returns (row_match (B, N), col_match (B, M)):
    each row/col used at most once, matched greedily best-first until
    ``threshold`` fails.  Static-shape: min(N, M) sequential rounds
    via lax.fori_loop.
    """
    import jax.lax as lax

    b, n, m = dist.shape
    sign = 1.0 if is_ascend else -1.0
    big = jnp.asarray(1e30, dist.dtype)
    rounds = min(n, m) if topk <= 0 else min(topk, min(n, m))

    def body(_, carry):
        d, rmatch, cmatch = carry
        flat = d.reshape(b, n * m)
        best = jnp.argmin(flat, axis=1) if is_ascend \
            else jnp.argmax(flat, axis=1)
        bi = jnp.arange(b)
        val = flat[bi, best]
        ok = (val <= threshold) if is_ascend else (val >= threshold)
        r, c = best // m, best % m
        rmatch = rmatch.at[bi, r].set(
            jnp.where(ok & (rmatch[bi, r] < 0), c.astype(jnp.float32),
                      rmatch[bi, r]))
        cmatch = cmatch.at[bi, c].set(
            jnp.where(ok & (cmatch[bi, c] < 0), r.astype(jnp.float32),
                      cmatch[bi, c]))
        # burn the taken row AND column: +big hides cells from argmin
        # (ascend, sign=1), -big from argmax (descend, sign=-1)
        d = jnp.where(ok[:, None, None]
                      & ((jnp.arange(n)[None, :, None] == r[:, None, None])
                         | (jnp.arange(m)[None, None, :]
                            == c[:, None, None])),
                      sign * big, d)
        return d, rmatch, cmatch

    rmatch0 = jnp.full((b, n), -1.0, jnp.float32)
    cmatch0 = jnp.full((b, m), -1.0, jnp.float32)
    _, rmatch, cmatch = lax.fori_loop(
        0, rounds, body, (dist.astype(jnp.float32)
                          if dist.dtype != jnp.float32 else dist,
                          rmatch0, cmatch0))
    return rmatch, cmatch


@register("_contrib_PSROIPooling", num_inputs=2)
def psroi_pooling(data, rois, *, spatial_scale=1.0, output_dim=0,
                  pooled_size=7, group_size=0):
    """Position-sensitive ROI pooling (parity:
    mx.nd.contrib.PSROIPooling; reference
    ``src/operator/contrib/psroi_pooling.cc`` — R-FCN's head).

    data: (N, k*k*output_dim, H, W) position-sensitive score maps;
    rois: (R, 5) rows [batch_idx, x1, y1, x2, y2] in image coords.
    Output (R, output_dim, k, k): bin (i, j) average-pools its spatial
    region from channel group ``(i*k + j)`` — every bin reads a
    DIFFERENT channel slice, which is the position-sensitivity.
    Static-shape: bins are averaged with a per-roi normalized mask
    matmul over the full H, W extent (dense MXU work).
    """
    k = int(pooled_size)
    gs = int(group_size) if group_size else k
    if gs != k:
        raise NotImplementedError("PSROIPooling: group_size != "
                                  "pooled_size")
    n, ctot, h, w = data.shape
    od = int(output_dim) if output_dim else ctot // (k * k)

    def one_roi(roi):
        bidx = roi[0].astype("int32")
        # reference rounds ROI coords BEFORE the scale
        x1 = jnp.round(roi[1]) * spatial_scale
        y1 = jnp.round(roi[2]) * spatial_scale
        x2 = jnp.round(roi[3]) * spatial_scale
        y2 = jnp.round(roi[4]) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w, bin_h = rw / k, rh / k
        # reference channel layout is output_dim-MAJOR:
        # channel = (ctop*k + gh)*k + gw
        img = data[bidx].reshape(od, k, k, h, w)

        ys = jnp.arange(h, dtype=jnp.float32) + 0.5
        xs = jnp.arange(w, dtype=jnp.float32) + 0.5
        out = []
        for i in range(k):          # static k: unrolled bin masks
            for j in range(k):
                y_lo, y_hi = y1 + i * bin_h, y1 + (i + 1) * bin_h
                x_lo, x_hi = x1 + j * bin_w, x1 + (j + 1) * bin_w
                my = ((ys >= jnp.floor(y_lo))
                      & (ys < jnp.ceil(y_hi))).astype(data.dtype)
                mx_ = ((xs >= jnp.floor(x_lo))
                       & (xs < jnp.ceil(x_hi))).astype(data.dtype)
                mask = my[:, None] * mx_[None, :]
                denom = jnp.maximum(mask.sum(), 1.0)
                grp = img[:, i, j]                # (od, h, w)
                out.append((grp * mask).sum(axis=(1, 2)) / denom)
        return jnp.stack(out, axis=-1).reshape(od, k, k)

    return jax.vmap(one_roi)(rois.astype(jnp.float32))


@register("_contrib_MultiProposal", num_inputs=3, num_outputs=2)
def multi_proposal(cls_prob, bbox_pred, im_info, *, rpn_pre_nms_top_n=6000,
                   rpn_post_nms_top_n=300, threshold=0.7,
                   rpn_min_size=16, scales=(4, 8, 16, 32),
                   ratios=(0.5, 1, 2), feature_stride=16,
                   output_score=False, iou_loss=False):
    """RPN proposal generation (parity: mx.nd.contrib.MultiProposal /
    Proposal; reference ``src/operator/contrib/multi_proposal.cc``).

    ``iou_loss=True`` (the reference's corner-offset decode) is not
    implemented and raises.  cls_prob (B, 2A, H, W) softmax scores
    (bg first A, fg last A);
    bbox_pred (B, 4A, H, W) anchor deltas; im_info (B, 3) rows
    [height, width, scale].  Returns (B*post_nms, 5) rows
    [batch_idx, x1, y1, x2, y2] (+ scores when ``output_score``) —
    static shape: images with fewer NMS survivors than ``post_nms``
    pad by repeating their top proposal (whole-image box at score 0
    when nothing survives the min-size filter).
    """
    if iou_loss:
        raise NotImplementedError(
            "MultiProposal: iou_loss=True (IoUTransformInv decode) "
            "is not implemented")
    b, c2, h, w = cls_prob.shape
    a = c2 // 2
    base = float(feature_stride)

    # exact reference base-anchor math (generate_anchors):
    def _whctr(an):
        return (an[2] - an[0] + 1, an[3] - an[1] + 1,
                an[0] + 0.5 * (an[2] - an[0]),
                an[1] + 0.5 * (an[3] - an[1]))

    def _mkanchor(ws_, hs_, xc, yc):
        return [xc - 0.5 * (ws_ - 1), yc - 0.5 * (hs_ - 1),
                xc + 0.5 * (ws_ - 1), yc + 0.5 * (hs_ - 1)]

    base_anchor = (0.0, 0.0, base - 1, base - 1)
    w0, h0, xc, yc = _whctr(base_anchor)
    rows = []
    for r in ratios:
        size = w0 * h0
        ws_ = float(np.round(np.sqrt(size / r)))
        hs_ = float(np.round(ws_ * r))
        for s in scales:
            rows.append(_mkanchor(ws_ * s, hs_ * s, xc, yc))
    banch = jnp.asarray(rows, jnp.float32)            # (A, 4)

    shift_x = jnp.arange(w, dtype=jnp.float32) * feature_stride
    shift_y = jnp.arange(h, dtype=jnp.float32) * feature_stride
    sx, sy = jnp.meshgrid(shift_x, shift_y)           # (H, W)
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1)     # (H, W, 4)
    all_anchors = (shifts[:, :, None, :]
                   + banch[None, None, :, :])         # (H, W, A, 4)
    anchors_flat = all_anchors.reshape(-1, 4)         # (H*W*A, 4)

    fg = cls_prob[:, a:].transpose(0, 2, 3, 1).reshape(b, -1)
    deltas = bbox_pred.transpose(0, 2, 3, 1).reshape(b, -1, 4)

    # decode with the Faster-RCNN coder (std=1, center form)
    aw = anchors_flat[:, 2] - anchors_flat[:, 0] + 1.0
    ah = anchors_flat[:, 3] - anchors_flat[:, 1] + 1.0
    ax = anchors_flat[:, 0] + aw * 0.5
    ay = anchors_flat[:, 1] + ah * 0.5
    px = deltas[..., 0] * aw + ax
    py = deltas[..., 1] * ah + ay
    pw = jnp.exp(jnp.minimum(deltas[..., 2], 10.0)) * aw
    ph = jnp.exp(jnp.minimum(deltas[..., 3], 10.0)) * ah
    x1 = px - 0.5 * (pw - 1)
    y1 = py - 0.5 * (ph - 1)
    x2 = px + 0.5 * (pw - 1)
    y2 = py + 0.5 * (ph - 1)

    imh = im_info[:, 0][:, None]
    imw = im_info[:, 1][:, None]
    x1 = jnp.clip(x1, 0, imw - 1)
    y1 = jnp.clip(y1, 0, imh - 1)
    x2 = jnp.clip(x2, 0, imw - 1)
    y2 = jnp.clip(y2, 0, imh - 1)
    min_size = rpn_min_size * im_info[:, 2][:, None]
    keep = ((x2 - x1 + 1 >= min_size)
            & (y2 - y1 + 1 >= min_size))
    scores = jnp.where(keep, fg, -1.0)

    n_all = scores.shape[1]
    # reference semantics: top_n <= 0 means "keep everything"
    n_pre = n_all if int(rpn_pre_nms_top_n) <= 0 \
        else min(int(rpn_pre_nms_top_n), n_all)
    n_post = n_pre if int(rpn_post_nms_top_n) <= 0 \
        else int(rpn_post_nms_top_n)

    # batched pre-NMS top-k, then ONE vmapped box_nms call (it vmaps
    # over leading batch dims) instead of a per-image traced loop
    order = jnp.argsort(-scores, axis=1)[:, :n_pre]     # (B, n_pre)
    take = lambda v: jnp.take_along_axis(v, order, axis=1)
    rows = jnp.stack([take(scores), take(x1), take(y1), take(x2),
                      take(y2)], axis=-1)               # (B, n_pre, 5)
    kept = box_nms(rows, overlap_thresh=threshold, valid_thresh=0.0,
                   topk=-1, coord_start=1, score_index=0, id_index=-1,
                   force_suppress=True)
    # box_nms suppresses IN PLACE (rows become -1 at their sorted
    # position) — COMPACT the survivors to the front before the
    # static n_post window, or scattered survivors past n_post are
    # lost and replaced by duplicates (recall collapse)
    valid = kept[:, :, 0] > 0
    comp = jnp.argsort(~valid, axis=1, stable=True)     # valid first
    kept = jnp.take_along_axis(kept, comp[:, :, None], axis=1)
    valid = jnp.take_along_axis(valid, comp, axis=1)

    if kept.shape[1] < n_post:
        pad_n = n_post - kept.shape[1]
        kept = jnp.concatenate(
            [kept, jnp.broadcast_to(kept[:, :1],
                                    (b, pad_n, 5))], axis=1)
        valid = jnp.concatenate(
            [valid, jnp.zeros((b, pad_n), bool)], axis=1)
    sel = kept[:, :n_post]
    valid = valid[:, :n_post]
    # pad invalid tail rows with the image's TOP proposal; when an
    # image has NO valid proposal (everything min-size-filtered), fall
    # back to the whole-image box at score 0 — never -1 garbage that
    # poisons downstream ROI pooling
    top = sel[:, :1]
    whole = jnp.stack(
        [jnp.zeros((b,)), jnp.zeros((b,)), jnp.zeros((b,)),
         imw[:, 0] - 1, imh[:, 0] - 1], axis=-1)[:, None]  # (B,1,5)
    any_valid = valid.any(axis=1)[:, None, None]
    fill = jnp.where(any_valid, top, whole.astype(sel.dtype))
    sel = jnp.where(valid[:, :, None], sel, fill)

    bcol = jnp.broadcast_to(
        jnp.arange(b, dtype=sel.dtype)[:, None, None], (b, n_post, 1))
    proposals = jnp.concatenate([bcol, sel[:, :, 1:5]],
                                axis=-1).reshape(b * n_post, 5)
    out_scores = jnp.maximum(sel[:, :, 0:1],
                             0.0).reshape(b * n_post, 1)
    return proposals, out_scores


@register("_contrib_Proposal", num_inputs=3)
def proposal(cls_prob, bbox_pred, im_info, *, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False):
    """Single-output RPN proposals (reference ``proposal.cc``; the
    commonly ported name).  Returns the (B*post_nms, 5) proposals
    NDArray directly like the reference's default; callers needing
    scores use MultiProposal (whose second output is always wired
    here — the registry has static output counts)."""
    if output_score:
        raise NotImplementedError(
            "Proposal: output_score=True — use MultiProposal, whose "
            "scores output is always available")
    props, _ = multi_proposal(
        cls_prob, bbox_pred, im_info,
        rpn_pre_nms_top_n=rpn_pre_nms_top_n,
        rpn_post_nms_top_n=rpn_post_nms_top_n, threshold=threshold,
        rpn_min_size=rpn_min_size, scales=scales, ratios=ratios,
        feature_stride=feature_stride, iou_loss=iou_loss)
    return props
