"""Operator registry: the nnvm op-registry equivalent.

Capability parity: reference nnvm ``Op`` registry + ``NNVM_REGISTER_OP``
attrs (``FCompute``/``FGradient``/``FInferShape``...) — SURVEY.md §2.1/§2.2.
TPU-native design: an op is a *pure JAX function* ``fcompute(*arrays,
**attrs)``.  Shape/dtype inference falls out of ``jax.eval_shape`` (symbolic
mode) or eager dispatch (imperative mode); gradients fall out of ``jax.vjp``;
kernel selection/fusion belongs to XLA.  Hand-written attrs the reference
needed per-op (inplace options, resource requests, storage type dispatch)
have no TPU analog and are deliberately absent.

Every op registered here is exposed in BOTH ``mx.nd.*`` and ``mx.sym.*``
namespaces (generated in ``mxnet_tpu.ndarray`` / ``mxnet_tpu.symbol``), the
way the reference codegens ``gen_op`` stubs from the C registry.
"""
from __future__ import annotations

import inspect
from typing import Callable, Dict, Optional, Sequence

__all__ = ["OpDef", "register", "get_op", "list_ops", "alias",
           "validate_opdef"]


class OpDef:
    """One operator.

    Attributes:
      name: canonical op name (MXNet spelling, e.g. ``broadcast_add``).
      fcompute: pure function ``(*jax_arrays, **attrs) -> array | tuple``.
      num_inputs: fixed arity or None for variadic (e.g. ``concat``).
      num_outputs: number of outputs (>=2 means fcompute returns a tuple).
      scalar_attrs: names of attrs that hold *dynamic* numeric values; the
        frontend passes them as 0-d device arrays appended to inputs so that
        changing them (e.g. learning rate) does NOT recompile.  fcompute
        receives them as trailing positional arrays.
      scalar_ref_input: index of the tensor input whose dtype anchors
        integer scalar attrs (e.g. `int_array + 1` stays int); None means
        "no tensor input is a dtype anchor" (RNG ops, whose first input is
        the uint32 key) — scalars are then float32.
      wrap_ctx: init-style op with no tensor inputs (zeros/ones/...);
        frontend must supply ctx/dtype.
    """

    __slots__ = ("name", "fcompute", "num_inputs", "num_outputs",
                 "scalar_attrs", "wrap_ctx", "doc", "attr_names",
                 "scalar_ref_input", "input_names", "scalar_defaults")

    def __init__(self, name: str, fcompute: Callable,
                 num_inputs: Optional[int], num_outputs: int,
                 scalar_attrs: Sequence[str], wrap_ctx: bool,
                 scalar_ref_input: Optional[int] = 0):
        self.name = name
        self.fcompute = fcompute
        self.num_inputs = num_inputs
        self.num_outputs = num_outputs
        self.scalar_attrs = tuple(scalar_attrs)
        self.scalar_ref_input = scalar_ref_input
        self.wrap_ctx = wrap_ctx
        self.doc = fcompute.__doc__ or ""
        try:
            sig = inspect.signature(fcompute)
            self.attr_names = tuple(
                p.name for p in sig.parameters.values()
                if p.kind == p.KEYWORD_ONLY)
            # positional params = tensor-input names (then scalar attrs);
            # used by the symbol frontend to map named inputs (data=...,
            # weight=...) to positions, the way the reference's op
            # signatures do
            pos = [p.name for p in sig.parameters.values()
                   if p.kind in (p.POSITIONAL_ONLY,
                                 p.POSITIONAL_OR_KEYWORD)]
            n_scal = len(self.scalar_attrs)
            self.input_names = tuple(pos[:len(pos) - n_scal]) \
                if n_scal else tuple(pos)
            # signature defaults for scalar attrs: lets the frontend
            # fill OMITTED scalars positionally so a partial kwarg set
            # can never misbind (e.g. t provided but wd omitted)
            self.scalar_defaults = {
                p.name: p.default
                for p in sig.parameters.values()
                if p.name in self.scalar_attrs
                and p.default is not inspect.Parameter.empty}
        except (TypeError, ValueError):
            self.attr_names = ()
            self.input_names = ()
            self.scalar_defaults = {}


_REGISTRY: Dict[str, OpDef] = {}
_ALIASES: Dict[str, str] = {}


def _signature_facts(fcompute: Callable):
    """(positional param names, has *args, has **kwargs), or None when the
    callable defeats introspection (C builtins)."""
    try:
        sig = inspect.signature(fcompute)
    except (TypeError, ValueError):
        return None
    params = list(sig.parameters.values())
    pos = [p.name for p in params
           if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    has_varpos = any(p.kind == p.VAR_POSITIONAL for p in params)
    has_varkw = any(p.kind == p.VAR_KEYWORD for p in params)
    return pos, has_varpos, has_varkw


def validate_opdef(op: OpDef):
    """Contract checks between an OpDef and its fcompute signature.

    Returns a list of ``(kind, message)`` violations (empty = valid),
    where ``kind`` is one of ``"arity"``, ``"scalar_attrs"``,
    ``"scalar_ref_input"``, ``"num_outputs"`` — a stable tag the static
    analyzer maps to its rule IDs (never dispatch on the prose).
    ``register()`` raises on any; ``mxnet_tpu.analysis`` re-runs the same
    checks offline so hand-built / monkeypatched OpDefs are caught by
    mxlint too.
    """
    problems = []
    if op.num_outputs == 0 or op.num_outputs < -1:
        problems.append((
            "num_outputs",
            f"num_outputs must be >= 1 (or -1 for dynamic), got "
            f"{op.num_outputs}"))
    ns = len(op.scalar_attrs)
    if ns and op.scalar_ref_input is not None:
        if op.num_inputs is not None and not \
                (0 <= op.scalar_ref_input < op.num_inputs):
            problems.append((
                "scalar_ref_input",
                f"scalar_ref_input={op.scalar_ref_input} out of bounds "
                f"for num_inputs={op.num_inputs}"))
    facts = _signature_facts(op.fcompute)
    if facts is None:
        return problems
    pos, has_varpos, _ = facts
    if not has_varpos:
        # scalar attrs bind POSITIONALLY after the tensor inputs: the
        # trailing positional params must carry exactly these names, or
        # scalar_defaults lookup and named-input mapping silently miss
        if ns:
            trailing = tuple(pos[len(pos) - ns:]) if len(pos) >= ns else ()
            if trailing != tuple(op.scalar_attrs):
                problems.append((
                    "scalar_attrs",
                    f"scalar_attrs {tuple(op.scalar_attrs)} must name the "
                    f"trailing positional params, got {trailing}"))
        if op.num_inputs is not None and len(pos) != op.num_inputs + ns:
            problems.append((
                "arity",
                f"fcompute has {len(pos)} positional params; expected "
                f"num_inputs ({op.num_inputs}) + scalar_attrs ({ns})"))
    return problems


def register(name: str, num_inputs: Optional[int] = 1, num_outputs: int = 1,
             scalar_attrs: Sequence[str] = (), wrap_ctx: bool = False,
             scalar_ref_input: Optional[int] = 0):
    """Decorator: register ``fcompute`` as operator ``name``.

    Fails fast on contract violations (see ``validate_opdef``): a bad
    ``scalar_ref_input`` or a ``scalar_attrs`` name that does not match
    the fcompute signature would otherwise surface much later as a wrong
    value silently bound to the wrong parameter.
    """

    def deco(fn: Callable) -> Callable:
        if name in _REGISTRY:
            raise ValueError(f"op {name!r} registered twice")
        op = OpDef(name, fn, num_inputs, num_outputs,
                   scalar_attrs, wrap_ctx, scalar_ref_input)
        problems = validate_opdef(op)
        if problems:
            raise ValueError(
                f"op {name!r} registration invalid: "
                + "; ".join(msg for _, msg in problems))
        _REGISTRY[name] = op
        return fn

    return deco


def alias(new_name: str, existing: str):
    """Register a second public name for an existing op (e.g. relu)."""
    _ALIASES[new_name] = existing


def get_op(name: str) -> OpDef:
    name = _ALIASES.get(name, name)
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"operator {name!r} is not registered") from None


def list_ops():
    return sorted(set(_REGISTRY) | set(_ALIASES))
