"""ctypes bindings for the native runtime ``libmxtpu.so``.

Capability parity: the reference's ``python/mxnet/base.py`` ctypes layer
over ``libmxnet.so`` (SURVEY.md §2.5 "FFI base").  The library is built
from ``src/`` (``make -C src``); when absent (fresh checkout without a
toolchain) everything degrades to the pure-Python paths — feature-gated
exactly like the reference's optional components.

Surfaces bound here:

* ``NativeEngine``   — threaded var-based dependency engine (host-side
  scheduling: data pipeline, IO, callbacks).
* ``NativeStorage``  — pooled host allocator with stats.
* ``NativeRecordIO`` — fast record framing (used by mxnet_tpu.recordio
  when available).
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Callable, List, Optional

__all__ = ["lib", "available", "NativeEngine", "NativeStorage",
           "NativeRecordIO", "build"]

_LIB_PATH = os.path.join(os.path.dirname(__file__), "lib", "libmxtpu.so")
_IMG_LIB_PATH = os.path.join(os.path.dirname(__file__), "lib",
                             "libmxtpu_image.so")
# single source of truth for the PJRT core path (pjrt_native imports it)
_PJRT_LIB_PATH = os.path.join(os.path.dirname(__file__), "lib",
                              "libmxtpu_pjrt.so")
lib = None
_img_lib = None      # False = tried and failed; loaded CDLL otherwise
_build_attempted = False


def _src_dir():
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src")


# sources that feed their own optional lib, not libmxtpu.so; a missing
# optional lib counts as stale (its toolchain dep — OpenCV, the PJRT
# headers — may have appeared since the last build; make skips the
# target harmlessly when it still can't build)
_AUX_LIBS = {
    "image_aug.cc": _IMG_LIB_PATH,
    "pjrt_executor.cc": _PJRT_LIB_PATH,
}


def _stale() -> bool:
    """True when a built lib is missing or older than ITS sources
    (aux sources feed their own .so — comparing them against
    libmxtpu.so would re-run make forever)."""
    if not os.path.exists(_LIB_PATH):
        return True
    src = _src_dir()
    try:
        lib_m = os.path.getmtime(_LIB_PATH)
        for f in os.listdir(src):
            if not f.endswith(".cc"):
                continue
            path = _AUX_LIBS.get(f)
            if path is not None:
                if not os.path.exists(path) or \
                        os.path.getmtime(os.path.join(src, f)) > \
                        os.path.getmtime(path):
                    return True
                continue
            if os.path.getmtime(os.path.join(src, f)) > lib_m:
                return True
        return False
    except OSError:
        return False


def _try_load():
    global lib, _build_attempted
    if lib is not None:
        return lib
    # the binary is NOT committed (platform-specific); build it from
    # src/ on first use and rebuild whenever the sources are newer.
    # flock serializes concurrent builders (pytest-xdist, forked
    # DataLoader workers) and keeps CDLL from seeing a half-written .so
    lock_path = _LIB_PATH + ".lock"
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    try:
        lock_f = open(lock_path, "w")
        import fcntl
        fcntl.flock(lock_f, fcntl.LOCK_EX)
    except OSError:
        lock_f = None
    try:
        if not _build_attempted and os.path.isdir(_src_dir()) \
                and _stale():
            _build_attempted = True
            import subprocess
            try:
                subprocess.run(["make", "-C", _src_dir()],
                               capture_output=True, timeout=300)
            except Exception:
                pass
        if os.path.exists(_LIB_PATH):
            try:
                lib = ctypes.CDLL(_LIB_PATH)
                _declare(lib)
            except OSError:
                lib = None
    finally:
        if lock_f is not None:
            import fcntl
            fcntl.flock(lock_f, fcntl.LOCK_UN)
            lock_f.close()
    return lib


def build():
    """Compile src/ → mxnet_tpu/lib/libmxtpu.so (needs g++)."""
    import subprocess
    subprocess.run(["make", "-C", _src_dir()], check=True)
    return _try_load() is not None


def available() -> bool:
    return _try_load() is not None


_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


def _declare(L):
    L.MXTPUEngineCreate.restype = ctypes.c_void_p
    L.MXTPUEngineCreate.argtypes = [ctypes.c_int]
    L.MXTPUEngineFree.argtypes = [ctypes.c_void_p]
    L.MXTPUEngineNewVar.restype = ctypes.c_uint64
    L.MXTPUEngineNewVar.argtypes = [ctypes.c_void_p]
    L.MXTPUEnginePush.restype = ctypes.c_uint64
    L.MXTPUEnginePush.argtypes = [
        ctypes.c_void_p, _CB, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
    L.MXTPUEngineWaitForVar.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    L.MXTPUEngineWaitForAll.argtypes = [ctypes.c_void_p]
    L.MXTPUEngineVarVersion.restype = ctypes.c_uint64
    L.MXTPUEngineVarVersion.argtypes = [ctypes.c_void_p, ctypes.c_uint64]

    L.MXTPUStorageCreate.restype = ctypes.c_void_p
    L.MXTPUStorageCreate.argtypes = [ctypes.c_int]
    L.MXTPUStorageFree.argtypes = [ctypes.c_void_p]
    L.MXTPUStorageAlloc.restype = ctypes.c_void_p
    L.MXTPUStorageAlloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    L.MXTPUStorageDealloc.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    L.MXTPUStorageReleaseAll.argtypes = [ctypes.c_void_p]
    for f in ("MXTPUStorageUsedBytes", "MXTPUStoragePoolBytes",
              "MXTPUStorageTotalAllocs"):
        getattr(L, f).restype = ctypes.c_uint64
        getattr(L, f).argtypes = [ctypes.c_void_p]

    L.MXTPURecordIOCreate.restype = ctypes.c_void_p
    L.MXTPURecordIOCreate.argtypes = [ctypes.c_char_p, ctypes.c_int]
    L.MXTPURecordIOFree.argtypes = [ctypes.c_void_p]
    L.MXTPURecordIOTell.restype = ctypes.c_int64
    L.MXTPURecordIOTell.argtypes = [ctypes.c_void_p]
    L.MXTPURecordIOSeek.restype = ctypes.c_int
    L.MXTPURecordIOSeek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    L.MXTPURecordIOWrite.restype = ctypes.c_int
    L.MXTPURecordIOWrite.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64]
    L.MXTPURecordIORead.restype = ctypes.c_int64
    L.MXTPURecordIORead.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte))]

    L.MXTPUGetLastError.restype = ctypes.c_char_p
    L.MXTPUGetVersion.restype = ctypes.c_int
    L.MXTPUHasFeature.restype = ctypes.c_int
    L.MXTPUHasFeature.argtypes = [ctypes.c_char_p]


class NativeEngine:
    """Var-based dependency engine (parity: Engine::Get() semantics).

    ``push(fn, read_vars, write_vars)`` schedules ``fn`` on the worker
    pool once every var grants access (readers share, writers exclusive,
    FIFO per var) — the reference's exact dataflow rule.
    """

    def __init__(self, num_workers=4):
        L = _try_load()
        if L is None:
            raise RuntimeError("libmxtpu.so not built; run "
                               "mxnet_tpu._native.build()")
        self._lib = L
        self._h = L.MXTPUEngineCreate(num_workers)
        # keep callbacks alive until executed
        self._cbs = {}
        self._cb_lock = threading.Lock()
        self._next = 0

    def new_var(self) -> int:
        return self._lib.MXTPUEngineNewVar(self._h)

    def push(self, fn: Callable[[], None], read_vars: List[int] = (),
             write_vars: List[int] = ()):
        with self._cb_lock:
            token = self._next
            self._next += 1

        def trampoline(_ctx, _token=token):
            try:
                fn()
            finally:
                with self._cb_lock:
                    self._cbs.pop(_token, None)

        cb = _CB(trampoline)
        with self._cb_lock:
            self._cbs[token] = cb
        r = (ctypes.c_uint64 * len(read_vars))(*read_vars)
        w = (ctypes.c_uint64 * len(write_vars))(*write_vars)
        return self._lib.MXTPUEnginePush(self._h, cb, None, r,
                                         len(read_vars), w,
                                         len(write_vars))

    def wait_for_var(self, var: int):
        self._lib.MXTPUEngineWaitForVar(self._h, var)

    def wait_for_all(self):
        self._lib.MXTPUEngineWaitForAll(self._h)

    def var_version(self, var: int) -> int:
        return self._lib.MXTPUEngineVarVersion(self._h, var)

    def close(self):
        # atomic handle swap under the lock: close() is reachable from
        # a pool's off-thread drain AND from __del__ on the GC thread —
        # the naive check-then-free raced them into a double
        # MXTPUEngineFree (observed segfault when several DataLoader
        # pools were collected while one was still draining)
        with self._cb_lock:
            h, self._h = self._h, None
        if h:
            self._lib.MXTPUEngineFree(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeStorage:
    """Pooled host allocator (parity: Storage::Get()->Alloc/Free)."""

    def __init__(self, pooled=True):
        L = _try_load()
        if L is None:
            raise RuntimeError("libmxtpu.so not built")
        self._lib = L
        self._h = L.MXTPUStorageCreate(1 if pooled else 0)

    def alloc(self, size: int) -> int:
        return self._lib.MXTPUStorageAlloc(self._h, size)

    def free(self, ptr: int):
        self._lib.MXTPUStorageDealloc(self._h, ptr)

    def release_all(self):
        self._lib.MXTPUStorageReleaseAll(self._h)

    @property
    def used_bytes(self):
        return self._lib.MXTPUStorageUsedBytes(self._h)

    @property
    def pool_bytes(self):
        return self._lib.MXTPUStoragePoolBytes(self._h)

    @property
    def total_allocs(self):
        return self._lib.MXTPUStorageTotalAllocs(self._h)

    def close(self):
        if self._h:
            self._lib.MXTPUStorageFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordIO:
    """Fast recordio framing (same byte format as mxnet_tpu.recordio)."""

    def __init__(self, path: str, writable: bool):
        L = _try_load()
        if L is None:
            raise RuntimeError("libmxtpu.so not built")
        self._lib = L
        self._h = L.MXTPURecordIOCreate(path.encode(), 1 if writable
                                        else 0)
        if not self._h:
            raise IOError(f"cannot open {path}")

    def tell(self) -> int:
        return self._lib.MXTPURecordIOTell(self._h)

    def seek(self, pos: int):
        if self._lib.MXTPURecordIOSeek(self._h, pos) != 0:
            raise IOError("seek failed")

    def write(self, data: bytes):
        if self._lib.MXTPURecordIOWrite(self._h, data, len(data)) != 0:
            raise IOError("write failed")

    def read(self) -> Optional[bytes]:
        out = ctypes.POINTER(ctypes.c_ubyte)()
        n = self._lib.MXTPURecordIORead(self._h, ctypes.byref(out))
        if n == -1:
            return None  # clean EOF
        if n < 0:
            from .base import MXNetError
            raise MXNetError("invalid record: corrupt or truncated")
        return ctypes.string_at(out, n)

    def close(self):
        if self._h:
            self._lib.MXTPURecordIOFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# native image decode/augment stage (src/image_aug.cc — reference
# iter_image_recordio_2.cc + image_aug_default.cc).  Separate .so so
# the core runtime has no OpenCV dependency; loads lazily and fails
# soft on systems without it.
# ---------------------------------------------------------------------------


def _try_load_image():
    global _img_lib
    if _img_lib is None:
        _try_load()  # triggers the make that also builds the image lib
        if os.path.exists(_IMG_LIB_PATH):
            try:
                L = ctypes.CDLL(_IMG_LIB_PATH)
                L.MXTPUImageAugAvailable.restype = ctypes.c_int
                L.MXTPUImageLastError.restype = ctypes.c_char_p
                L.MXTPUImageDecodeAugment.restype = ctypes.c_int
                L.MXTPUImageDecodeAugment.argtypes = [
                    ctypes.c_char_p, ctypes.c_longlong, ctypes.c_int,
                    ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_int, ctypes.c_double, ctypes.c_double,
                    ctypes.c_int, ctypes.POINTER(ctypes.c_float),
                    ctypes.POINTER(ctypes.c_float),
                    ctypes.POINTER(ctypes.c_float)]
                _img_lib = L
            except OSError:
                _img_lib = False
        else:
            _img_lib = False
    return _img_lib or None


def image_available() -> bool:
    return _try_load_image() is not None


def decode_augment(buf, crop_w, crop_h, resize=0, interp=2, to_rgb=1,
                   rand_x=-1.0, rand_y=-1.0, mirror=0, mean=None,
                   std=None):
    """Decode + augment ONE encoded image into a float32 CHW array.

    The whole stage runs in C++ with the GIL released (ctypes drops it
    for the call), so pool workers get true parallel decode — the
    reference's preprocess_threads behavior, natively."""
    import numpy as np
    L = _try_load_image()
    if L is None:
        raise RuntimeError("native image stage unavailable "
                           "(libmxtpu_image.so not built)")
    out = np.empty((3, int(crop_h), int(crop_w)), np.float32)
    fp = ctypes.POINTER(ctypes.c_float)

    def vec3(v):
        if v is None:
            return None
        a = np.asarray(v, np.float32).reshape(-1)
        if a.size == 1:
            a = np.repeat(a, 3)     # scalar broadcasts over channels
        if a.size != 3:
            raise ValueError(
                f"mean/std must have 1 or 3 elements, got {a.size}")
        return (ctypes.c_float * 3)(*a)

    buf = bytes(buf)
    rc = L.MXTPUImageDecodeAugment(
        buf, len(buf), int(to_rgb), int(resize), int(interp),
        int(crop_w), int(crop_h), float(rand_x), float(rand_y),
        int(mirror), vec3(mean), vec3(std),
        out.ctypes.data_as(fp))
    if rc != 0:
        from .base import MXNetError
        raise MXNetError("native decode_augment failed: "
                         + L.MXTPUImageLastError().decode())
    return out
