"""Checkpoint backends (SURVEY.md §5 "Checkpoint / resume").

Three serialization surfaces exist for parity (``mx.nd.save/load``,
Gluon ``save_parameters``/``export``, Module checkpoints); this module
adds the TPU-NATIVE backend: orbax-style async sharded checkpointing for
big sharded models, where each host writes its shards and restore
re-shards onto the current mesh.

``save_checkpoint``/``load_checkpoint`` also provide the reference's
``mx.model`` free-function checkpoint API surface.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

from .base import MXNetError
from . import ndarray as nd
from .ndarray.ndarray import NDArray

__all__ = ["save_checkpoint", "load_checkpoint", "OrbaxCheckpoint"]


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Parity: mx.model.save_checkpoint."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    payload = {f"arg:{k}": v for k, v in arg_params.items()}
    payload.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", payload)


def load_checkpoint(prefix, epoch):
    """Parity: mx.model.load_checkpoint → (symbol, arg_params,
    aux_params)."""
    from . import symbol as sym_mod
    symbol = None
    if os.path.exists(f"{prefix}-symbol.json"):
        symbol = sym_mod.load(f"{prefix}-symbol.json")
    saved = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in saved.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return symbol, arg_params, aux_params


class OrbaxCheckpoint:
    """Orbax-style array-dict checkpointing (TPU-native backend).

    Saves/restores a dict of NDArrays (e.g. ``block.collect_params()``
    data + trainer states).  The store is the elastic shard format
    (``elastic.manager.write_arrays``): each save commits via temp-dir
    + rename (a crash never leaves a half-written checkpoint visible)
    and every shard carries its sha256 — ``load`` rejects partial or
    corrupt content with a clear ``MXNetError`` instead of loading
    garbage.  For whole-trainer state (optimizer, RNG, step counters,
    mesh layout) use :class:`mxnet_tpu.elastic.CheckpointManager`,
    which this class is a thin array-only wrapper over.
    """

    def __init__(self, directory):
        self.directory = os.path.abspath(directory)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, str(step))

    def save(self, step: int, arrays: Dict[str, NDArray], force=True):
        from .elastic import manager as _mgr
        path = self._path(step)
        if os.path.exists(path) and not force:
            raise MXNetError(
                f"checkpoint step {step} already exists at {path} "
                "(pass force=True to overwrite)")
        return _mgr.write_arrays(
            path, {k: (v._data if isinstance(v, NDArray) else v)
                   for k, v in arrays.items()},
            extra={"step": int(step)})

    def load(self, step: int, ctx=None) -> Dict[str, NDArray]:
        from .elastic import manager as _mgr
        _manifest, hosts = _mgr.read_arrays(self._path(step))
        return {k: nd.array(v, ctx=ctx) for k, v in hosts.items()}

    def load_into(self, step: int, params) -> None:
        """Restore directly into a ParameterDict (buffer swap keeps
        autograd leaves)."""
        loaded = self.load(step)
        for name, p in params.items():
            if name in loaded:
                p.set_data(loaded[name])
