"""Checkpoint backends (SURVEY.md §5 "Checkpoint / resume").

Three serialization surfaces exist for parity (``mx.nd.save/load``,
Gluon ``save_parameters``/``export``, Module checkpoints); this module
adds the TPU-NATIVE backend: orbax-style async sharded checkpointing for
big sharded models, where each host writes its shards and restore
re-shards onto the current mesh.

``save_checkpoint``/``load_checkpoint`` also provide the reference's
``mx.model`` free-function checkpoint API surface.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

from .base import MXNetError
from . import ndarray as nd
from .ndarray.ndarray import NDArray

__all__ = ["save_checkpoint", "load_checkpoint", "OrbaxCheckpoint"]


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Parity: mx.model.save_checkpoint."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    payload = {f"arg:{k}": v for k, v in arg_params.items()}
    payload.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", payload)


def load_checkpoint(prefix, epoch):
    """Parity: mx.model.load_checkpoint → (symbol, arg_params,
    aux_params)."""
    from . import symbol as sym_mod
    symbol = None
    if os.path.exists(f"{prefix}-symbol.json"):
        symbol = sym_mod.load(f"{prefix}-symbol.json")
    saved = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in saved.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return symbol, arg_params, aux_params


class OrbaxCheckpoint:
    """Async sharded checkpointing over orbax (TPU-native backend).

    Saves/restores a dict of NDArrays (e.g. ``block.collect_params()``
    data + trainer states); sharded jax arrays are written shard-wise per
    host and re-sharded on restore.  Falls back with a clear error when
    orbax is unavailable.
    """

    def __init__(self, directory):
        try:
            import orbax.checkpoint as ocp
        except ImportError as e:
            raise MXNetError(
                "orbax-checkpoint is not available in this "
                "environment") from e
        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._ckptr = ocp.PyTreeCheckpointer()

    def save(self, step: int, arrays: Dict[str, NDArray], force=True):
        tree = {k: v._data for k, v in arrays.items()}
        path = os.path.join(self.directory, str(step))
        self._ckptr.save(path, tree, force=force)
        return path

    def load(self, step: int, ctx=None) -> Dict[str, NDArray]:
        path = os.path.join(self.directory, str(step))
        tree = self._ckptr.restore(path)
        out = {}
        for k, v in tree.items():
            out[k] = nd.array(v)
        return out

    def load_into(self, step: int, params) -> None:
        """Restore directly into a ParameterDict (buffer swap keeps
        autograd leaves)."""
        loaded = self.load(step)
        for name, p in params.items():
            if name in loaded:
                p.set_data(loaded[name])
