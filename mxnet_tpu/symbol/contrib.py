"""``mx.sym.contrib`` — contrib namespace over symbol op wrappers
(parity: reference ``python/mxnet/symbol/contrib.py``).

Mirrors ``nd.contrib``'s resolution: plain names fall through to the
symbol module's generated wrappers; ops registered only under a
``_contrib_`` name (DeformableConvolution, MultiProposal, ...)
resolve through the prefixed registry entry.  Control-flow ops
(foreach/while_loop/cond) stay on the nd side — hybridized blocks
trace through nd, which is where those higher-order ops live.
"""
from __future__ import annotations


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    from .. import symbol as _sym
    try:
        return getattr(_sym, name)
    except AttributeError:
        pass
    prefixed = getattr(_sym, f"_contrib_{name}", None)
    if prefixed is not None:
        return prefixed
    raise AttributeError(name)
