"""``mx.sym`` namespace: Symbol + every registered operator as a function.

Capability parity: reference ``python/mxnet/symbol/`` (generated op stubs
over the C registry).  Wrappers mirror the nd namespace's convention —
symbol inputs lead (positional or as ``data=``/named kwargs), attrs follow
— and additionally accept ``name=`` for explicit node naming, exactly like
the reference.
"""
from __future__ import annotations

import sys

from ..ops.registry import get_op, list_ops, OpDef
from .symbol import (Symbol, Executor, var, Variable, Group, load,
                     load_json, _invoke, _AUX_INPUTS)

_mod = sys.modules[__name__]


def _make_wrapper(opname: str, op: OpDef):
    ordered_attrs = tuple(op.scalar_attrs) + tuple(op.attr_names)

    input_names = op.input_names

    def fn(*args, name=None, **kwargs):
        inputs = []
        attr_pos = []
        for a in args:
            if isinstance(a, Symbol):
                inputs.append(a)
            else:
                attr_pos.append(a)
        # symbol inputs may also arrive as keywords (data=..., weight=...);
        # map them to their declared positions, remaining order-stable for
        # names the op signature doesn't declare (variadic ops)
        named = {}
        for k in list(kwargs):
            if isinstance(kwargs[k], Symbol):
                named[k] = kwargs.pop(k)
        if named:
            for iname in input_names[len(inputs):]:
                if iname in named:
                    inputs.append(named.pop(iname))
            inputs.extend(named.values())
        for aname, val in zip(ordered_attrs, attr_pos):
            if aname in kwargs:
                raise TypeError(f"{opname}: got multiple values for "
                                f"{aname}")
            kwargs[aname] = val
        return _invoke(opname, inputs, kwargs, name=name)

    fn.__name__ = opname
    fn.__qualname__ = opname
    fn.__doc__ = op.doc
    return fn


def _generate(target_mod):
    for opname in list_ops():
        if opname in _CUSTOM:
            setattr(target_mod, opname, _CUSTOM[opname])
            continue
        op = get_op(opname)
        setattr(target_mod, opname, _make_wrapper(opname, op))


# ---------------------------------------------------------------------------
# ops that need frontend glue in the nd namespace keep the same names here;
# graph evaluation dispatches to the nd wrappers, so the node just records
# the call (see symbol._eval_graph)
# ---------------------------------------------------------------------------


def Dropout(data, p=0.5, mode="training", axes=(), name=None, **kwargs):
    return _invoke("Dropout", [data],
                   {"p": p, "mode": mode, "axes": tuple(axes)}, name=name)


def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
              momentum=0.9, fix_gamma=True, use_global_stats=False,
              output_mean_var=False, axis=1, name=None, **kwargs):
    return _invoke(
        "BatchNorm", [data, gamma, beta, moving_mean, moving_var],
        {"eps": eps, "momentum": momentum, "fix_gamma": fix_gamma,
         "use_global_stats": use_global_stats,
         "output_mean_var": output_mean_var, "axis": axis},
        name=name, num_outputs=3 if output_mean_var else 1)


def maximum(lhs, rhs, name=None):
    if isinstance(lhs, Symbol) and isinstance(rhs, Symbol):
        return _invoke("broadcast_maximum", [lhs, rhs], {}, name=name)
    if isinstance(lhs, Symbol):
        return _invoke("_maximum_scalar", [lhs], {"scalar": rhs}, name=name)
    return _invoke("_maximum_scalar", [rhs], {"scalar": lhs}, name=name)


def minimum(lhs, rhs, name=None):
    if isinstance(lhs, Symbol) and isinstance(rhs, Symbol):
        return _invoke("broadcast_minimum", [lhs, rhs], {}, name=name)
    if isinstance(lhs, Symbol):
        return _invoke("_minimum_scalar", [lhs], {"scalar": rhs}, name=name)
    return _invoke("_minimum_scalar", [rhs], {"scalar": lhs}, name=name)


def RNN(*args, **kwargs):
    raise NotImplementedError(
        "sym.RNN: use mx.gluon.rnn layers (scan-lowered)")


def zeros(shape, dtype="float32", name=None, **kwargs):
    return _invoke("_zeros", [], {"shape": tuple(shape), "dtype": dtype},
                   name=name)


def ones(shape, dtype="float32", name=None, **kwargs):
    return _invoke("_ones", [], {"shape": tuple(shape), "dtype": dtype},
                   name=name)


_CUSTOM = {"Dropout": Dropout, "BatchNorm": BatchNorm, "RNN": RNN,
           "maximum": maximum, "minimum": minimum}

_generate(_mod)

from . import contrib  # noqa: E402  (mirrors nd.contrib resolution)

__all__ = ["Symbol", "Executor", "var", "Variable", "Group", "load",
           "load_json", "contrib"]
