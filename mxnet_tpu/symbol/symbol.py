"""Symbol: declarative graph construction + the graph executor.

Capability parity: reference ``python/mxnet/symbol/symbol.py`` + nnvm graph
IR (``3rdparty/nnvm``) + ``src/executor/graph_executor.cc`` — SURVEY.md
§2.1 ("nnvm graph + passes", "Graph executor"), §2.5 ("Symbol API"), §3.4.

TPU-native design: a Symbol is a pure-Python DAG of op nodes over the SAME
op registry the imperative layer uses.  ``bind`` does not run nnvm passes —
shape/type inference is ``jax.eval_shape`` over the traced graph, memory
planning/fusion/layout belong to XLA, and the whole graph compiles to ONE
XLA program (the reference needed per-node OpExecutors + engine bulking to
approximate this; SURVEY.md §3.4's "segment & bulk" is free here).
Gradients: ``jax.vjp`` over the traced graph replaces the nnvm ``Gradient``
pass.  Auxiliary states (BatchNorm moving stats) reproduce the reference's
aux-array mutation observably via CachedOp-style version tracking.
"""
from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError, numeric_types
from ..context import Context, current_context
from ..ops.registry import get_op
from ..ndarray.ndarray import NDArray

__all__ = ["Symbol", "Executor", "var", "Variable", "Group", "load",
           "load_json"]


# ---------------------------------------------------------------------------
# naming
# ---------------------------------------------------------------------------

class _NameManager(threading.local):
    def __init__(self):
        self.counts = {}

    def get(self, hint: str) -> str:
        hint = hint.lower()
        n = self.counts.get(hint, 0)
        self.counts[hint] = n + 1
        return f"{hint}{n}"


_NAMES = _NameManager()

# ops whose nth..mth inputs are auxiliary states (not gradient targets);
# mirrors the reference's per-op aux declarations in src/operator/nn/*
_AUX_INPUTS = {"BatchNorm": (3, 4)}


class _Node:
    """One graph node: an op application or a variable."""

    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs",
                 "_user_attrs")

    def __init__(self, op: Optional[str], name: str, attrs: dict,
                 inputs: List[Tuple["_Node", int]], num_outputs: int = 1):
        self.op = op          # nd-namespace callable name; None for vars
        self.name = name
        self.attrs = attrs
        self.inputs = inputs
        self.num_outputs = num_outputs
        self._user_attrs = {}


def _aux_ids(heads: Sequence[_Node]):
    """Ids of variable nodes consumed in auxiliary-state positions.

    Aux-ness is a property of THIS graph's consuming edges — never a
    mutation of the (possibly shared) variable node, so using the same
    var in another graph keeps it an ordinary argument there.
    """
    out = set()
    for node in _topo(heads):
        positions = _AUX_INPUTS.get(node.op)
        if not positions:
            continue
        for pos in positions:
            if pos < len(node.inputs):
                inp = node.inputs[pos][0]
                if inp.op is None:
                    out.add(id(inp))
    return out


def _topo(heads: Sequence[_Node]) -> List[_Node]:
    seen = set()
    order: List[_Node] = []

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for inp, _ in node.inputs:
            visit(inp)
        order.append(node)

    for h in heads:
        visit(h)
    return order


class Symbol:
    """A (possibly multi-output) symbolic expression."""

    def __init__(self, outputs: List[Tuple[_Node, int]]):
        self._outputs = outputs

    # -- construction helpers --------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        outs = ", ".join(self.list_outputs())
        return f"<Symbol {outs}>"

    def __iter__(self):
        return (Symbol([o]) for o in self._outputs)

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError(f"no output named {index!r}; outputs are "
                                 f"{names}")
            index = names.index(index)
        if isinstance(index, (int, np.integer)):
            return Symbol([self._outputs[index]])
        raise TypeError("index must be int or str")

    def attr(self, key):
        if len(self._outputs) == 1:
            return self._outputs[0][0]._user_attrs.get(key)
        return None

    def _set_attr(self, **kwargs):
        for node, _ in self._outputs:
            node._user_attrs.update(kwargs)

    def attr_dict(self):
        out = {}
        for node in _topo([n for n, _ in self._outputs]):
            if node._user_attrs:
                out[node.name] = dict(node._user_attrs)
        return out

    # -- introspection ----------------------------------------------------
    def _head_nodes(self):
        return [n for n, _ in self._outputs]

    def list_arguments(self) -> List[str]:
        heads = self._head_nodes()
        aux = _aux_ids(heads)
        return [n.name for n in _topo(heads)
                if n.op is None and id(n) not in aux]

    def list_outputs(self) -> List[str]:
        names = []
        for node, idx in self._outputs:
            if node.op is None:
                names.append(node.name)
            elif node.num_outputs == 1:
                names.append(node.name + "_output")
            else:
                names.append(f"{node.name}_output{idx}")
        return names

    def list_auxiliary_states(self) -> List[str]:
        heads = self._head_nodes()
        aux = _aux_ids(heads)
        return [n.name for n in _topo(heads)
                if n.op is None and id(n) in aux]

    def list_inputs(self) -> List[str]:
        return [n.name for n in _topo(self._head_nodes()) if n.op is None]

    def get_internals(self) -> "Symbol":
        outs = []
        for node in _topo(self._head_nodes()):
            for i in range(node.num_outputs):
                outs.append((node, i))
        return Symbol(outs)

    def get_children(self) -> Optional["Symbol"]:
        kids = []
        for node, _ in self._outputs:
            kids.extend(node.inputs)
        return Symbol(kids) if kids else None

    # -- composition ------------------------------------------------------
    def __call__(self, *args, **kwargs):
        """Compose: replace this symbol's variable inputs (parity:
        ``Symbol.__call__`` / nnvm graph compose)."""
        if args and kwargs:
            raise MXNetError("compose accepts positional OR keyword "
                             "arguments, not both")
        arg_names = self.list_inputs()
        mapping: Dict[str, Symbol] = {}
        if args:
            if len(args) > len(arg_names):
                raise MXNetError("too many positional arguments to compose")
            mapping = dict(zip(arg_names, args))
        else:
            for k, v in kwargs.items():
                if k not in arg_names:
                    raise MXNetError(f"no input named {k!r}")
                mapping[k] = v
        for v in mapping.values():
            if not isinstance(v, Symbol) or len(v._outputs) != 1:
                raise MXNetError("compose values must be 1-output Symbols")

        memo: Dict[int, _Node] = {}

        def clone(node: _Node) -> Tuple[_Node, int]:
            if node.op is None and node.name in mapping:
                return mapping[node.name]._outputs[0]
            if id(node) in memo:
                return memo[id(node)], -1
            new_inputs = []
            for inp, idx in node.inputs:
                rep, ridx = clone(inp)
                new_inputs.append((rep, idx if ridx == -1 else ridx))
            if node.op is None:
                memo[id(node)] = node
                return node, -1
            nn = _Node(node.op, node.name, dict(node.attrs), new_inputs,
                       node.num_outputs)
            nn._user_attrs = dict(node._user_attrs)
            memo[id(nn)] = nn
            memo[id(node)] = nn
            return nn, -1

        outs = []
        for node, idx in self._outputs:
            rep, ridx = clone(node)
            outs.append((rep, idx if ridx == -1 else ridx))
        return Symbol(outs)

    # -- arithmetic sugar -------------------------------------------------
    def _binary(self, other, opname, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _invoke(opname, [a, b], {})
        if isinstance(other, numeric_types):
            return _invoke(scalar_op, [self], {"scalar": other})
        return NotImplemented

    def __add__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        if isinstance(o, numeric_types):
            return self._binary(o, None, "_rminus_scalar")
        return self._binary(o, "broadcast_sub", "_minus_scalar",
                            reverse=True)

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, o):
        if isinstance(o, numeric_types):
            return self._binary(o, None, "_rdiv_scalar")
        return self._binary(o, "broadcast_div", "_div_scalar", reverse=True)

    def __pow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return self._binary(-1.0, None, "_mul_scalar")

    # -- reshaping sugar (mirrors NDArray methods) ------------------------
    def reshape(self, shape):
        return _invoke("reshape", [self], {"shape": tuple(shape)})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _invoke("transpose", [self], {"axes": axes})

    # -- shape / type inference ------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """Returns (arg_shapes, out_shapes, aux_shapes), aligned with
        list_arguments()/list_outputs()/list_auxiliary_states()."""
        try:
            return self._infer_shape_impl(*args, **kwargs)
        except MXNetError:
            raise
        except Exception as e:
            raise MXNetError(f"infer_shape error: {e}") from e

    def infer_shape_partial(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(*args, **kwargs)
        except Exception:
            return None, None, None

    def _infer_shape_impl(self, *args, **kwargs):
        import jax

        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        if args:
            kwargs = dict(zip(arg_names, args))
        known = {k: tuple(v) for k, v in kwargs.items() if v is not None}

        # aux shapes follow from the ops that consume them (BN stats share
        # the gamma/beta channel dim); infer by evaluating with zeros of a
        # guessed channel size is fragile — instead walk BN nodes directly
        shapes = dict(known)
        out_struct, arg_shapes, aux_shapes = _infer_via_eval_shape(
            self, shapes, arg_names, aux_names)
        out_shapes = [tuple(int(d) for d in s.shape) for s in out_struct]
        return ([arg_shapes.get(n) for n in arg_names], out_shapes,
                [aux_shapes.get(n) for n in aux_names])

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        if args:
            kwargs = dict(zip(arg_names, args))
        dtypes = {k: np.dtype(v).name for k, v in kwargs.items()
                  if v is not None}
        default = "float32"
        arg_types = [np.dtype(dtypes.get(n, default)) for n in arg_names]
        # outputs: evaluate shapes+types together would need shapes; keep
        # the reference's common case (homogeneous float graphs)
        out_types = [np.dtype(default)] * len(self.list_outputs())
        aux_types = [np.dtype(default)] * len(self.list_auxiliary_states())
        return arg_types, out_types, aux_types

    # -- serialization ----------------------------------------------------
    def tojson(self) -> str:
        nodes = _topo(self._head_nodes())
        idx = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": n.op if n.op is not None else "null",
                "name": n.name,
                "attrs": {k: repr(v) for k, v in n.attrs.items()},
                "inputs": [[idx[id(i)], oi, 0] for i, oi in n.inputs],
                "num_outputs": n.num_outputs,
                "user_attrs": {k: repr(v)
                               for k, v in n._user_attrs.items()},
            })
        heads = [[idx[id(n)], oi, 0] for n, oi in self._outputs]
        return json.dumps({"nodes": jnodes, "heads": heads,
                           "arg_nodes": [i for i, n in enumerate(nodes)
                                         if n.op is None],
                           "mxtpu_version": 1}, indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- evaluation / binding --------------------------------------------
    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx or current_context(), kwargs)
        return ex.forward()

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, **_ignored) -> "Executor":
        return Executor(self, ctx, args, args_grad, grad_req, aux_states)

    def simple_bind(self, ctx=None, grad_req="write", **kwargs) -> "Executor":
        """Allocate argument/grad/aux arrays from inferred shapes."""
        from .. import ndarray as nd
        ctx = ctx or current_context()
        arg_names = self.list_arguments()
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if shape is None:
                raise MXNetError(f"simple_bind: cannot infer shape of "
                                 f"argument {name!r}; pass it explicitly")
            args[name] = nd.zeros(shape, ctx=ctx)
        aux = {}
        for name, shape in zip(self.list_auxiliary_states(), aux_shapes):
            aux[name] = nd.zeros(shape, ctx=ctx)
        args_grad = None
        if grad_req != "null":
            args_grad = {n: nd.zeros(a.shape, ctx=ctx)
                         for n, a in args.items()}
        return Executor(self, ctx, args, args_grad, grad_req, aux)


# ---------------------------------------------------------------------------
# graph evaluation (shared by Executor / infer_shape / SymbolBlock)
# ---------------------------------------------------------------------------


def _eval_graph(sym: Symbol, value_of: Dict[str, NDArray]):
    """Evaluate the DAG by dispatching through the nd namespace, so every
    frontend behaviour (RNG keys, BN aux mutation, scalar attrs) is shared
    with the imperative path."""
    from .. import ndarray as nd_mod

    cache: Dict[int, Tuple] = {}

    def ev(node: _Node) -> Tuple:
        got = cache.get(id(node))
        if got is not None:
            return got
        if node.op is None:
            try:
                val = value_of[node.name]
            except KeyError:
                raise MXNetError(
                    f"bind: no value provided for input {node.name!r}")
            res = (val,)
        else:
            ins = [ev(inp)[oi] for inp, oi in node.inputs]
            fn = getattr(nd_mod, node.op)
            out = fn(*ins, **node.attrs)
            res = tuple(out) if isinstance(out, (list, tuple)) else (out,)
        cache[id(node)] = res
        return res

    return [ev(node)[oi] for node, oi in sym._outputs]


def _param_shape_rules():
    """Per-op rules inferring unknown *parameter* input shapes from known
    data shapes + attrs (the nnvm InferShape pass's essential half; output
    shapes then fall out of jax.eval_shape)."""

    def fc(in_shapes, attrs, n_inputs):
        data = in_shapes[0]
        if data is None:
            return {}
        h = attrs.get("num_hidden")
        flatten = attrs.get("flatten", True)
        d = int(np.prod(data[1:])) if flatten else data[-1]
        out = {1: (h, d)}
        if n_inputs > 2:
            out[2] = (h,)
        return out

    def conv(in_shapes, attrs, n_inputs):
        data = in_shapes[0]
        if data is None:
            return {}
        f = attrs.get("num_filter")
        g = attrs.get("num_group", 1)
        kernel = tuple(attrs.get("kernel", ()))
        out = {1: (f, data[1] // g) + kernel}
        if n_inputs > 2:
            out[2] = (f,)
        return out

    def deconv(in_shapes, attrs, n_inputs):
        data = in_shapes[0]
        if data is None:
            return {}
        f = attrs.get("num_filter")
        g = attrs.get("num_group", 1)
        kernel = tuple(attrs.get("kernel", ()))
        out = {1: (data[1], f // g) + kernel}
        if n_inputs > 2:
            out[2] = (f,)
        return out

    def bn(in_shapes, attrs, n_inputs):
        data = in_shapes[0]
        if data is None:
            return {}
        c = data[attrs.get("axis", 1)]
        return {1: (c,), 2: (c,), 3: (c,), 4: (c,)}

    def norm_lastaxis(in_shapes, attrs, n_inputs):
        data = in_shapes[0]
        if data is None:
            return {}
        c = data[attrs.get("axis", -1)]
        return {i: (c,) for i in range(1, n_inputs)}

    def embedding(in_shapes, attrs, n_inputs):
        return {1: (attrs.get("input_dim"), attrs.get("output_dim"))}

    return {"FullyConnected": fc, "Convolution": conv,
            "Deconvolution": deconv, "BatchNorm": bn,
            "LayerNorm": norm_lastaxis, "InstanceNorm": norm_lastaxis,
            "RMSNorm": norm_lastaxis, "embedding": embedding}


_PARAM_SHAPE_RULES = _param_shape_rules()


def _propagate_shapes(sym, shapes, on_node_error=None, out_shapes=None):
    """Walk the graph in topo order, inferring unknown var shapes via the
    param rules and node output shapes via jax.eval_shape per node.

    ``on_node_error(node, in_shapes, exc)`` is invoked when a node's
    abstract evaluation raises (shape/dtype contract violation); the
    default keeps the historical behavior of skipping the node silently.
    ``out_shapes`` may be a dict to receive the per-(node, output-index)
    inferred shapes — the static analyzer uses it to tell "skipped
    because inputs unknown" from "evaluated clean".
    """
    import jax
    from .. import autograd
    from .. import ndarray as nd_mod

    if out_shapes is None:
        out_shapes: Dict[Tuple[int, int], tuple] = {}

    def in_shape(node, i):
        inp, oi = node.inputs[i]
        if inp.op is None:
            return shapes.get(inp.name)
        return out_shapes.get((id(inp), oi))

    for node in _topo(sym._head_nodes()):
        if node.op is None:
            # var(shape=...) hints participate in inference, matching
            # the reference's Symbol.var(shape=) behavior.  Dims <= 0
            # mean "unknown" (deferred-init params stamp e.g. (8, 0));
            # such hints must not pre-empt the param-shape rules below.
            hint = node._user_attrs.get("__shape__")
            if node.name not in shapes and hint is not None and \
                    all(int(d) > 0 for d in hint):
                shapes[node.name] = tuple(int(d) for d in hint)
            if node.name in shapes:
                out_shapes[(id(node), 0)] = tuple(shapes[node.name])
            continue
        ins = [in_shape(node, i) for i in range(len(node.inputs))]
        rule = _PARAM_SHAPE_RULES.get(node.op)
        if rule is not None:
            for pos, shape in rule(ins, node.attrs,
                                   len(node.inputs)).items():
                if pos < len(node.inputs):
                    vnode = node.inputs[pos][0]
                    if vnode.op is None and vnode.name not in shapes:
                        shapes[vnode.name] = tuple(
                            int(d) for d in shape)
                        ins[pos] = shapes[vnode.name]
        if any(s is None for s in ins):
            continue  # cannot evaluate this node yet

        def one_node(*vals, _node=node):
            value_of = {}
            shells = [NDArray(v, ctx=current_context()) for v in vals]
            fn = getattr(nd_mod, _node.op)
            with autograd.pause():
                out = fn(*shells, **_node.attrs)
            outs = tuple(out) if isinstance(out, (list, tuple)) else (out,)
            return tuple(o._data for o in outs)

        try:
            structs = [jax.ShapeDtypeStruct(s, np.dtype("float32"))
                       for s in ins]
            res = jax.eval_shape(one_node, *structs)
            for i, r in enumerate(res):
                out_shapes[(id(node), i)] = tuple(
                    int(d) for d in r.shape)
        except Exception as e:
            if on_node_error is not None:
                on_node_error(node, ins, e)
            continue
    return shapes


def _infer_via_eval_shape(sym, shapes, arg_names, aux_names):
    """Shape inference = jax.eval_shape over the traced graph."""
    import jax
    from .. import autograd

    all_names = arg_names + aux_names
    missing = [n for n in all_names if n not in shapes]
    if missing:
        _propagate_shapes(sym, shapes)
        missing = [n for n in all_names if n not in shapes]
        if missing:
            raise MXNetError(f"infer_shape: missing shapes for {missing}")

    structs = [jax.ShapeDtypeStruct(shapes[n], np.dtype("float32"))
               for n in all_names]

    def fn(*vals):
        value_of = {n: NDArray(v, ctx=current_context())
                    for n, v in zip(all_names, vals)}
        with autograd.pause():  # inference mode: no RNG keys, no mutation
            outs = _eval_graph(sym, value_of)
        return tuple(o._data for o in outs)

    out_struct = jax.eval_shape(fn, *structs)
    arg_shapes = {n: tuple(int(d) for d in shapes[n]) for n in arg_names}
    aux_shapes = {n: tuple(int(d) for d in shapes[n]) for n in aux_names}
    return out_struct, arg_shapes, aux_shapes


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------


class Executor:
    """Bound, compiled symbolic graph (parity: mx.executor.Executor).

    The forward (and fused forward+backward) run as single jitted XLA
    programs cached per (shapes, dtypes, train-mode); aux-state mutation
    (BN running stats) is detected via buffer-version tracking and written
    back after execution, reproducing engine-side aux updates.
    """

    def __init__(self, sym: Symbol, ctx, args, args_grad, grad_req,
                 aux_states):
        self._sym = sym
        self._ctx = ctx if isinstance(ctx, Context) else current_context()
        self.arg_names = sym.list_arguments()
        self.aux_names = sym.list_auxiliary_states()
        self.output_names = sym.list_outputs()

        self.arg_dict = self._to_dict(self.arg_names, args, "argument")
        self.aux_dict = self._to_dict(self.aux_names, aux_states or {},
                                      "auxiliary state", allow_missing=True)
        for name in self.aux_names:
            if name not in self.aux_dict:
                raise MXNetError(f"bind: missing auxiliary state {name!r}")

        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self.grad_req = dict(zip(self.arg_names, grad_req))
        else:
            self.grad_req = {n: grad_req.get(n, "null")
                             for n in self.arg_names}
        self.grad_dict = self._to_dict(
            self.arg_names, args_grad or {}, "gradient",
            allow_missing=True)

        self.outputs: List[NDArray] = []
        self._monitor_callback = None
        self._compiled = {}
        self._saved_inputs = None
        self._cached_grads = None

    def _to_dict(self, names, values, what, allow_missing=False):
        if isinstance(values, dict):
            out = OrderedDict()
            for n in names:
                if n in values:
                    out[n] = values[n]
                elif not allow_missing:
                    raise MXNetError(f"bind: missing {what} {n!r}")
            return out
        values = list(values)
        if not allow_missing and len(values) != len(names):
            raise MXNetError(
                f"bind: expected {len(names)} {what}s, got {len(values)}")
        return OrderedDict(zip(names, values))

    # -- compiled-program cache ------------------------------------------
    def _get_compiled(self, training: bool, with_grad: bool):
        import jax
        import jax.numpy as jnp
        from .. import autograd
        from .. import random as _rnd

        arg_vals = [self.arg_dict[n]._data for n in self.arg_names]
        aux_vals = [self.aux_dict[n]._data for n in self.aux_names]
        key = (tuple((v.shape, str(v.dtype)) for v in arg_vals),
               tuple((v.shape, str(v.dtype)) for v in aux_vals),
               training, with_grad)
        entry = self._compiled.get(key)
        if entry is not None:
            return entry

        sym = self._sym
        arg_names, aux_names = self.arg_names, self.aux_names
        ctx = self._ctx
        grad_mask = [self.grad_req.get(n, "null") != "null"
                     for n in arg_names]
        aux_mutated: List[int] = []
        monitor = self._monitor_callback
        monitor_names: List[str] = []

        def run_graph(avals, xvals, key_raw):
            key_counter = [0]

            def key_provider(_ctx):
                k = jax.random.fold_in(
                    jax.random.wrap_key_data(key_raw), key_counter[0])
                key_counter[0] += 1
                return NDArray(jax.random.key_data(k), ctx=ctx)

            value_of = {n: NDArray(v, ctx=ctx)
                        for n, v in zip(arg_names, avals)}
            aux_shells = {n: NDArray(v, ctx=ctx)
                          for n, v in zip(aux_names, xvals)}
            value_of.update(aux_shells)
            _rnd._push_key_provider(key_provider)
            prev = autograd.set_training(training)
            try:
                vers = {n: s._version for n, s in aux_shells.items()}
                outs = _eval_graph(sym, value_of)
                aux_mutated.clear()
                aux_mutated.extend(
                    i for i, n in enumerate(aux_names)
                    if aux_shells[n]._version != vers[n])
                new_aux = tuple(aux_shells[aux_names[i]]._data
                                for i in aux_mutated)
            finally:
                autograd.set_training(prev)
                _rnd._pop_key_provider()
            return tuple(o._data for o in outs), new_aux

        if not with_grad:
            def fwd(avals, xvals, key_raw):
                return run_graph(avals, xvals, key_raw)
            fn = jax.jit(fwd)
        else:
            def fwd_bwd(avals, xvals, key_raw, cots):
                def of_args(diff_vals):
                    full = list(avals)
                    di = iter(diff_vals)
                    for i, m in enumerate(grad_mask):
                        if m:
                            full[i] = next(di)
                    outs, new_aux = run_graph(tuple(full), xvals, key_raw)
                    return outs, new_aux

                diff_in = tuple(v for v, m in zip(avals, grad_mask) if m)
                outs, vjp, new_aux = jax.vjp(of_args, diff_in,
                                             has_aux=True)
                if cots is None:
                    cots = tuple(jnp.ones_like(o) for o in outs)
                (grads,) = vjp(cots)
                return outs, new_aux, grads
            fn = jax.jit(fwd_bwd)
        entry = (fn, aux_mutated)
        self._compiled[key] = entry
        return entry

    # -- API --------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """Run forward.  With ``is_train=True`` the FUSED fwd+bwd program
        runs once (default head cotangents) and the gradients are cached
        for ``backward()`` — the classic forward();backward() idiom costs
        one XLA execution, not two."""
        from .. import profiler
        with profiler._span(f"Executor.forward[train={bool(is_train)}]",
                            "executor") as sp:
            outs = self._forward_impl(is_train, **kwargs)
            sp.sync([o._data for o in outs])
            return outs

    def _forward_impl(self, is_train=False, **kwargs):
        from .. import random as _rnd
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"forward: unknown argument {k!r}")
            self.arg_dict[k]._set_data(
                v._data.astype(self.arg_dict[k].dtype.name)
                if isinstance(v, NDArray) else
                np.asarray(v, dtype=self.arg_dict[k].dtype))
        self._saved_inputs = None
        self._cached_grads = None
        if is_train:
            self.forward_backward(_write_grads=False)
            return self.outputs
        fn, aux_mutated = self._get_compiled(False, with_grad=False)
        key = _rnd._next_key_nd(self._ctx)
        avals = tuple(self.arg_dict[n]._data for n in self.arg_names)
        xvals = tuple(self.aux_dict[n]._data for n in self.aux_names)
        outs, new_aux = fn(avals, xvals, key._data)
        self._write_aux(aux_mutated, new_aux)
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        if self._monitor_callback is not None:
            for name, o in zip(self.output_names, self.outputs):
                self._monitor_callback(name, o)
        return self.outputs

    def backward(self, out_grads=None):
        if out_grads is None and self._cached_grads is not None:
            self._write_grads(self._cached_grads)
            self._cached_grads = None
            return
        if self._saved_inputs is None:
            raise MXNetError(
                "backward called before forward(is_train=True)")
        # re-run the fused program (explicit cotangents, or default ones
        # when the cached grads were already consumed)
        fn, _ = self._get_compiled(True, with_grad=True)
        avals, xvals, keyraw = self._saved_inputs
        if out_grads is None:
            cots = None
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cots = tuple(g._data for g in out_grads)
        outs, new_aux, grads = fn(avals, xvals, keyraw, cots)
        self._cached_grads = None
        self._write_grads(grads)
        return

    def forward_backward(self, out_grads=None, _write_grads=True,
                         **kwargs):
        """Fused one-program forward+backward (the Module.fit hot path)."""
        from .. import random as _rnd
        for k, v in kwargs.items():
            self.arg_dict[k]._set_data(
                v._data if isinstance(v, NDArray)
                else np.asarray(v, dtype=self.arg_dict[k].dtype))
        fn, aux_mutated = self._get_compiled(True, with_grad=True)
        key = _rnd._next_key_nd(self._ctx)
        avals = tuple(self.arg_dict[n]._data for n in self.arg_names)
        xvals = tuple(self.aux_dict[n]._data for n in self.aux_names)
        cots = None
        if out_grads is not None:
            cots = tuple(g._data for g in out_grads)
        outs, new_aux, grads = fn(avals, xvals, key._data, cots)
        self._write_aux(aux_mutated, new_aux)
        if _write_grads:
            self._write_grads(grads)
            self._cached_grads = None
        else:
            self._cached_grads = grads
        self._saved_inputs = (avals, xvals, key._data)
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        if self._monitor_callback is not None:
            for name, o in zip(self.output_names, self.outputs):
                self._monitor_callback(name, o)
        return self.outputs

    def _write_aux(self, aux_mutated, new_aux):
        # aux_mutated holds the aux indices that mutated, captured at trace
        # time by run_graph (populated during the jit's first execution)
        for i, v in zip(aux_mutated, new_aux):
            self.aux_dict[self.aux_names[i]]._set_data(v)

    def _write_grads(self, grads):
        gi = iter(grads)
        for n in self.arg_names:
            if self.grad_req.get(n, "null") == "null":
                continue
            g = next(gi)
            dst = self.grad_dict.get(n)
            if dst is None:
                continue
            if self.grad_req[n] == "add":
                dst._set_data(dst._data + g)
            else:
                dst._set_data(g.astype(dst.dtype.name))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in arg_params.items():
            if k in self.arg_dict:
                v.copyto(self.arg_dict[k])
            elif not allow_extra_params:
                raise MXNetError(f"unknown argument {k!r}")
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    v.copyto(self.aux_dict[k])
                elif not allow_extra_params:
                    raise MXNetError(f"unknown aux state {k!r}")

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback
        self._compiled.clear()

    def reshape(self, **kwargs):
        return self  # shapes re-specialize automatically via the jit cache


# ---------------------------------------------------------------------------
# free functions
# ---------------------------------------------------------------------------


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs) -> Symbol:
    node = _Node(None, name, {}, [])
    if attr:
        node._user_attrs.update(attr)
    for k, v in (("__shape__", shape), ("__lr_mult__", lr_mult),
                 ("__wd_mult__", wd_mult), ("__dtype__", dtype)):
        if v is not None:
            node._user_attrs[k] = v
    return Symbol([(node, 0)])


Variable = var


def Group(symbols) -> Symbol:
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def _invoke(opname, sym_inputs, attrs, name=None, num_outputs=None):
    """Create an op node (shared by generated sym.* wrappers)."""
    nodes = []
    for s in sym_inputs:
        if not isinstance(s, Symbol):
            raise MXNetError(f"{opname}: symbolic op inputs must be "
                             f"Symbols, got {type(s)}")
        if len(s._outputs) != 1:
            raise MXNetError(f"{opname}: multi-output Symbol used as input;"
                             " select an output first")
        nodes.append(s._outputs[0])
    if num_outputs is None:
        try:
            num_outputs = get_op(opname).num_outputs
        except KeyError:
            num_outputs = 1
    name = name or _NAMES.get(opname.lstrip("_"))
    node = _Node(opname, name, dict(attrs), nodes, num_outputs)
    return Symbol([(node, i) for i in range(num_outputs)]) \
        if num_outputs > 1 else Symbol([(node, 0)])


# re-export for __init__ namespace generation
def _invoke_sym(opname, sym_inputs, attrs, name=None):
    return _invoke(opname, sym_inputs, attrs, name=name)


def load_json(json_str: str) -> Symbol:
    import ast
    data = json.loads(json_str)
    nodes: List[_Node] = []
    for jn in data["nodes"]:
        attrs = {}
        for k, v in jn.get("attrs", {}).items():
            try:
                attrs[k] = ast.literal_eval(v)
            except (ValueError, SyntaxError):
                attrs[k] = v
        op = jn["op"]
        node = _Node(None if op == "null" else op, jn["name"], attrs,
                     [(nodes[i], oi) for i, oi, _ in jn["inputs"]],
                     jn.get("num_outputs", 1))
        for k, v in jn.get("user_attrs", {}).items():
            try:
                node._user_attrs[k] = ast.literal_eval(v)
            except (ValueError, SyntaxError):
                node._user_attrs[k] = v
        nodes.append(node)
    return Symbol([(nodes[i], oi) for i, oi, _ in data["heads"]])


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())
