"""Hybridizable control-flow ops: foreach / while_loop / cond.

Capability parity: reference ``src/operator/control_flow.cc`` (SURVEY.md
§2.2 "Control-flow ops") — higher-order ops taking Python bodies, making
RNN-style loops graph-compilable.  TPU-native design: they lower DIRECTLY
to ``lax.scan`` / masked-scan / ``lax.cond`` — the exact mapping SURVEY.md
calls out — so a loop is one fused XLA region, not per-iteration dispatch.

Two integration points make these behave like the reference's ops:

* **Closure capture.** The reference cuts the body subgraph and collects
  its free variables so gradients flow to parameters used inside a loop
  body.  Here a capture scope (ndarray.invoke hook) detects every external
  NDArray the body touches during a shape-only dry trace; those arrays
  become explicit differentiable inputs via CachedOp-style buffer swap.
* **Autograd.** Under ``autograd.record()`` the whole control-flow op is
  ONE tape node whose vjp is ``jax.vjp`` of the lowered function —
  gradients flow through scan/cond to data, states, and captured params.

``while_loop`` lowers to a *masked* ``lax.scan`` over ``max_iterations``
(once the predicate turns false, carries stop updating): reverse-mode
differentiable and TPU-friendly, where ``lax.while_loop`` would forbid
backward.  The reference also required ``max_iterations`` imperatively.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..base import MXNetError
from . import ndarray as nd_core
from .ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond"]


def __getattr__(name):
    """Registry-op passthrough: ``nd.contrib.box_nms`` etc. resolve to
    the same generated wrappers as ``nd.box_nms``; ops registered ONLY
    under a ``_contrib_`` name (DeformableConvolution) resolve through
    the prefixed registry entry."""
    if name.startswith("__"):
        raise AttributeError(name)
    from .. import ndarray as _nd
    try:
        return getattr(_nd, name)
    except AttributeError:
        pass
    prefixed = getattr(_nd, f"_contrib_{name}", None)
    if prefixed is not None:
        return prefixed
    raise AttributeError(name)


class _CaptureScope:
    """Records external NDArrays observed by invoke() during a dry trace."""

    def __init__(self, internal):
        self._internal = {id(x) for x in internal}
        self.captured: List[NDArray] = []
        self._captured_ids = set()

    def observe(self, inputs):
        for x in inputs:
            # views capture their BASE: the buffer swap in _swap() writes
            # `_buf`, which views read through `_base` — capturing the view
            # itself would leave the base a constant and zero its grads
            base = x
            while base._base is not None:
                base = base._base
            if id(base) not in self._internal and \
                    id(base) not in self._captured_ids:
                self._captured_ids.add(id(base))
                self.captured.append(base)

    def mark_internal(self, arrays):
        for a in arrays:
            self._internal.add(id(a))


def _detect_captures(run, shells):
    """Dry-run `run` under jax.eval_shape with a capture scope active."""
    import jax

    scope = _CaptureScope(shells)

    def dry(*vals):
        for s, v in zip(shells, vals):
            s._buf = v
        outs = run()
        return tuple(o._data for o in outs)

    prev = nd_core._capture_scope
    nd_core._capture_scope = scope
    saved = [(s._buf, s._version) for s in shells]
    try:
        jax.eval_shape(dry, *[jax.ShapeDtypeStruct(s.shape, s.dtype)
                              for s in shells])
    finally:
        nd_core._capture_scope = prev
        for s, (buf, ver) in zip(shells, saved):
            s._buf = buf
            s._version = ver
    return scope.captured


def _dispatch(fn, explicit: Sequence[NDArray], captured: Sequence[NDArray],
              ctx):
    """Run `fn(*vals)` (pure) with autograd-tape integration."""
    import jax
    from .. import autograd
    from .. import engine

    arrays = [x._data for x in explicit] + [c._data for c in captured]
    if autograd.is_recording():
        outs_data, raw_vjp = jax.vjp(fn, *arrays)

        def vjp_fn(cots, _fn=raw_vjp):
            # fn always returns a tuple; the tape passes a bare cotangent
            # for single-output nodes
            return _fn(cots if isinstance(cots, tuple) else (cots,))

        node = autograd._Node(vjp_fn, list(explicit) + list(captured), 0,
                              [o.aval for o in outs_data])
        outs = []
        for i, d in enumerate(outs_data):
            o = NDArray(d, ctx=ctx)
            o._ag_node = node
            o._ag_out_idx = i
            outs.append(o)
        node.outputs = list(outs)
        return outs
    outs_data = fn(*arrays)
    for d in outs_data:
        engine.track(d)
    return [NDArray(d, ctx=ctx) for d in outs_data]


def _swap(captured, vals):
    saved = [(c._buf, c._version) for c in captured]
    for c, v in zip(captured, vals):
        c._buf = v
        c._version += 1  # invalidate any view's cached slice
    return saved


def _restore(captured, saved):
    for c, (buf, ver) in zip(captured, saved):
        c._buf = buf
        c._version = ver


def foreach(body, data, init_states):
    """Scan `body` over axis 0 of `data` (parity: mx.nd.contrib.foreach).

    ``body(data_slice, states) -> (outputs, new_states)``.  Lowered to one
    ``lax.scan``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    data_is_list = isinstance(data, (list, tuple))
    data_list = list(data) if data_is_list else [data]
    states_is_list = isinstance(init_states, (list, tuple))
    states = list(init_states) if states_is_list else [init_states]
    ctx = data_list[0].context
    length = data_list[0].shape[0]
    if length == 0:
        raise MXNetError("foreach: zero-length data")

    # shells the dry trace and the scan body will rebind per step
    x_shells = [NDArray(d._data[0], ctx=ctx) for d in data_list]
    s_shells = [NDArray(s._data, ctx=ctx) for s in states]

    out_struct = {}

    def run_body():
        x_in = x_shells if data_is_list else x_shells[0]
        s_in = list(s_shells) if states_is_list else s_shells[0]
        outs, new_states = body(x_in, s_in)
        outs_l = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        ns_l = list(new_states) if isinstance(new_states, (list, tuple)) \
            else [new_states]
        out_struct["n_out"] = len(outs_l)
        out_struct["out_is_list"] = isinstance(outs, (list, tuple))
        return outs_l + ns_l

    captured = _detect_captures(run_body, x_shells + s_shells)
    n_data, n_states = len(data_list), len(states)

    def fn(*vals):
        dvals = vals[:n_data]
        svals = vals[n_data:n_data + n_states]
        cvals = vals[n_data + n_states:]
        saved = _swap(captured, cvals)
        try:
            def scan_body(carry, xs):
                for sh, v in zip(x_shells, xs):
                    sh._buf = v
                    sh._version += 1
                for sh, v in zip(s_shells, carry):
                    sh._buf = v
                    sh._version += 1
                res = run_body()
                outs = [r._data for r in res[:out_struct["n_out"]]]
                new_carry = tuple(r._data
                                  for r in res[out_struct["n_out"]:])
                return new_carry, tuple(outs)

            final_carry, ys = lax.scan(scan_body, tuple(svals),
                                       tuple(dvals))
        finally:
            _restore(captured, saved)
        return tuple(ys) + tuple(final_carry)

    res = _dispatch(fn, data_list + states, captured, ctx)
    n_out = out_struct["n_out"]
    outs, final_states = res[:n_out], res[n_out:]
    outs = outs if out_struct["out_is_list"] else outs[0]
    final_states = list(final_states) if states_is_list else final_states[0]
    return outs, final_states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Parity: mx.nd.contrib.while_loop.

    ``cond(*loop_vars) -> scalar``; ``func(*loop_vars) -> (step_output,
    new_loop_vars)``.  Returns ``(outputs, final_loop_vars)`` where outputs
    are stacked over ``max_iterations`` steps (rows past the loop's actual
    length hold the last computed values' padding, zeros — matching the
    reference's "gaps filled with zeros" contract).
    """
    import jax.numpy as jnp
    from jax import lax

    if max_iterations is None:
        raise MXNetError("while_loop: max_iterations is required")
    lv_is_list = isinstance(loop_vars, (list, tuple))
    lvs = list(loop_vars) if lv_is_list else [loop_vars]
    ctx = lvs[0].context

    v_shells = [NDArray(v._data, ctx=ctx) for v in lvs]
    out_struct = {}

    def run_body():
        res = func(*v_shells)
        if not (isinstance(res, tuple) and len(res) == 2):
            raise MXNetError("while_loop: func must return "
                             "(step_output, new_loop_vars)")
        step_out, new_vars = res
        so_l = [] if step_out is None else (
            list(step_out) if isinstance(step_out, (list, tuple))
            else [step_out])
        nv_l = list(new_vars) if isinstance(new_vars, (list, tuple)) \
            else [new_vars]
        out_struct["n_out"] = len(so_l)
        out_struct["out_is_list"] = isinstance(step_out, (list, tuple))
        return so_l + nv_l

    def run_cond():
        return [cond(*v_shells)]

    captured = _detect_captures(run_body, v_shells)
    cap_cond = _detect_captures(run_cond, v_shells)
    for c in cap_cond:
        if all(c is not k for k in captured):
            captured.append(c)
    n_vars = len(lvs)

    def fn(*vals):
        vvals = vals[:n_vars]
        cvals = vals[n_vars:]
        saved = _swap(captured, cvals)
        try:
            def scan_body(carry, _):
                active, vs = carry
                for sh, v in zip(v_shells, vs):
                    sh._buf = v
                    sh._version += 1
                c = cond(*v_shells)._data.reshape(()) != 0
                act = jnp.logical_and(active, c)
                res = run_body()
                n_out = out_struct["n_out"]
                outs = tuple(
                    jnp.where(act, r._data,
                              jnp.zeros_like(r._data))
                    for r in res[:n_out])
                new_vs = tuple(
                    jnp.where(act, r._data, v)
                    for r, v in zip(res[n_out:], vs))
                return (act, new_vs), outs

            init = (jnp.asarray(True), tuple(vvals))
            (active, final_vs), ys = lax.scan(
                scan_body, init, None, length=max_iterations)
        finally:
            _restore(captured, saved)
        return tuple(ys) + tuple(final_vs)

    res = _dispatch(fn, lvs, captured, ctx)
    n_out = out_struct["n_out"]
    outs, final_vars = res[:n_out], res[n_out:]
    outs = list(outs) if out_struct["out_is_list"] else \
        (outs[0] if outs else [])
    final_vars = list(final_vars) if lv_is_list else final_vars[0]
    return outs, final_vars


def cond(pred, then_func, else_func):
    """Parity: mx.nd.contrib.cond — ``pred`` scalar NDArray (or callable
    returning one); branch closures take no arguments."""
    from jax import lax

    if callable(pred):
        pred_nd = pred()
    else:
        pred_nd = pred
    if not isinstance(pred_nd, NDArray):
        raise MXNetError("cond: pred must be (a callable returning) an "
                         "NDArray scalar")
    ctx = pred_nd.context

    out_struct = {}

    def run_then():
        r = then_func()
        l = list(r) if isinstance(r, (list, tuple)) else [r]
        out_struct["n_out"] = len(l)
        out_struct["out_is_list"] = isinstance(r, (list, tuple))
        return l

    def run_else():
        r = else_func()
        return list(r) if isinstance(r, (list, tuple)) else [r]

    cap_then = _detect_captures(run_then, [])
    cap_else = _detect_captures(run_else, [])
    captured = list(cap_then)
    for c in cap_else:
        if all(c is not k for k in captured):
            captured.append(c)

    def fn(pred_val, *cvals):
        saved = _swap(captured, cvals)
        try:
            def t_branch(_):
                return tuple(r._data for r in run_then())

            def e_branch(_):
                return tuple(r._data for r in run_else())

            outs = lax.cond(pred_val.reshape(()) != 0, t_branch, e_branch,
                            operand=None)
        finally:
            _restore(captured, saved)
        return outs

    res = _dispatch(fn, [pred_nd], captured, ctx)
    return res if out_struct["out_is_list"] else res[0]


def boolean_mask(data, index, axis=0):
    """Select rows where ``index`` is nonzero (parity:
    mx.nd.contrib.boolean_mask).  Output shape depends on the DATA —
    like ``np.unique`` this computes the row set on the host (a sync
    point; the reference's dynamic-shape op has the same
    non-hybridizable character)."""
    import numpy as _np
    mask = _np.asarray(index.asnumpy()).astype(bool)
    if mask.shape[0] != data.shape[axis]:
        raise MXNetError(
            f"boolean_mask: mask length {mask.shape[0]} != data dim "
            f"{data.shape[axis]} along axis {axis}")
    keep = _np.nonzero(mask)[0]
    idx = nd_core.array(keep.astype("int32"), ctx=data.context,
                        dtype="int32")
    from ..ops.registry import get_op
    return nd_core.invoke(get_op("take"), [data, idx], axis=axis,
                          mode="clip")


def fft(data, *, compute_size=128):
    """Batched 1-D FFT over the last axis with the reference's
    interleaved real/imag output layout (parity: mx.nd.contrib.fft —
    output (..., 2n): [re0, im0, re1, im1, ...])."""
    return nd_core.invoke(_fft_opdef(), [data])


def ifft(data, *, compute_size=128):
    """Inverse of :func:`fft` (parity: mx.nd.contrib.ifft): input
    interleaved real/imag (..., 2n) → real (..., n), scaled by n like
    the reference (which does NOT normalize, so fft→ifft gains a
    factor n — reproduced faithfully)."""
    return nd_core.invoke(_ifft_opdef(), [data])


import functools as _functools


@_functools.lru_cache(maxsize=None)
def _fft_opdef():
    import jax.numpy as jnp
    from ..ops.registry import OpDef

    def fc(x):
        f = jnp.fft.fft(x.astype(jnp.float32), axis=-1)
        out = jnp.stack([f.real, f.imag], axis=-1)
        return out.reshape(x.shape[:-1] + (2 * x.shape[-1],)) \
            .astype(x.dtype)

    return OpDef("_contrib_fft_impl", fc, 1, 1, (), False, None)


@_functools.lru_cache(maxsize=None)
def _ifft_opdef():
    import jax.numpy as jnp
    from ..ops.registry import OpDef

    def fc(x):
        n = x.shape[-1] // 2
        pairs = x.reshape(x.shape[:-1] + (n, 2)).astype(jnp.float32)
        z = pairs[..., 0] + 1j * pairs[..., 1]
        # reference ifft does not divide by n: reproduce (fft∘ifft = n·x)
        return (jnp.fft.ifft(z, axis=-1).real * n).astype(x.dtype)

    return OpDef("_contrib_ifft_impl", fc, 1, 1, (), False, None)


__all__ += ["boolean_mask", "fft", "ifft"]
