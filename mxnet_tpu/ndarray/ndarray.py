"""NDArray: imperative, mutable, device-resident n-dimensional array.

Capability parity: reference ``src/ndarray/ndarray.cc`` +
``include/mxnet/ndarray.h`` + ``python/mxnet/ndarray/ndarray.py``
(SURVEY.md §2.1, §2.5).  TPU-native design (SURVEY.md §7 hard-part 1):

* The reference's ref-counted ``Chunk`` (storage handle + engine var) becomes
  a *versioned buffer slot*: mutation = functional update producing a new
  ``jax.Array`` swapped into the slot with a version bump.  PJRT's async
  runtime provides the dataflow ordering the threaded engine provided; the
  version counter reproduces the observable ordering for *views*.
* Views (``x[1:3]``, ``x[0]``) share the base slot: reads re-slice lazily
  against the base's current version; writes scatter into the base.  This
  reproduces MXNet's view-write-through semantics without shared memory.
* ``wait_to_read()``/``asnumpy()`` are the sync points; async runtime errors
  surface there (exception teleporting — PJRT native behaviour).
* In-place mutation while ``autograd.record()`` is active raises, exactly as
  the reference does.
"""
from __future__ import annotations

import builtins
import json
import struct
from typing import List, Optional, Sequence, Union

import numpy as np

from ..base import MXNetError, numeric_types
from ..context import Context, current_context
from .. import engine
from ..ops.registry import OpDef, get_op

__all__ = ["NDArray", "invoke", "array", "empty", "zeros", "ones", "full",
           "arange", "eye", "concatenate", "save", "load", "waitall",
           "moveaxis"]

_FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")


def _jnp():
    import jax.numpy as jnp
    return jnp


def _jax():
    import jax
    return jax


# ---------------------------------------------------------------------------
# NDArray
# ---------------------------------------------------------------------------


class NDArray:
    """Mutable device array.

    Non-view arrays own a buffer slot (``_buf`` + ``_version``); views hold a
    reference to their base plus a basic-indexing key.
    """

    __slots__ = ("_buf", "_version", "_ctx", "_base", "_index",
                 "_cached_view", "_cached_ver",
                 "grad_req", "_grad", "_ag_node", "_ag_out_idx",
                 "_deferred_init", "__weakref__")

    # make NumPy defer to NDArray.__radd__ etc.
    __array_priority__ = 100.0

    def __init__(self, data, ctx: Optional[Context] = None,
                 _base: "NDArray" = None, _index=None):
        self._base = _base
        self._index = _index
        self._cached_view = None
        self._cached_ver = -1
        self.grad_req = "null"
        self._grad = None
        self._ag_node = None
        self._ag_out_idx = 0
        self._deferred_init = None
        if _base is not None:
            self._buf = None
            self._version = 0
            self._ctx = _base._ctx
        else:
            self._buf = data
            self._version = 0
            self._ctx = ctx if ctx is not None else current_context()

    # -- buffer access ----------------------------------------------------
    @property
    def _data(self):
        """Current jax.Array value (lazily re-sliced for views)."""
        if self._base is not None:
            base = self._base
            if self._cached_ver != base._root_version():
                self._cached_view = base._data[self._index]
                self._cached_ver = base._root_version()
            return self._cached_view
        return self._buf

    def _root_version(self):
        return (self._base._root_version() if self._base is not None
                else self._version)

    def _set_data(self, new):
        """Mutate: swap buffer (or scatter through the view chain)."""
        if self._base is not None:
            base_val = self._base._data
            self._base._set_data(base_val.at[self._index].set(new))
        else:
            self._buf = new
            self._version += 1
            engine.track(new)

    # -- basic properties -------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def context(self) -> Context:
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def T(self):
        return invoke(get_op("transpose"), [self])

    @property
    def grad(self):
        return self._grad

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        try:
            body = str(self.asnumpy())
        except Exception as e:  # async error teleports here
            raise
        return f"\n{body}\n<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of an NDArray with multiple "
                             "elements is ambiguous.")
        return bool(self.asscalar())

    # -- sync points ------------------------------------------------------
    def asnumpy(self) -> np.ndarray:
        """Copy to host; THE sync point (parity: WaitToRead + copy).

        Async device-side failures (the op was dispatched long ago)
        surface HERE as MXNetError — the reference engine's
        exception-teleporting contract (test_exc_handling.py upstream).
        """
        try:
            return np.asarray(self._data)
        except MXNetError:
            raise
        except Exception as e:
            raise MXNetError(
                f"async execution error surfaced at asnumpy(): {e}"
            ) from e

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        try:
            _jax().block_until_ready(self._data)
        except MXNetError:
            raise
        except Exception as e:
            raise MXNetError(
                f"async execution error surfaced at wait_to_read(): {e}"
            ) from e

    def wait_to_write(self):
        self.wait_to_read()

    # -- jax interop (TPU-native extension) -------------------------------
    @property
    def jax(self):
        """The underlying ``jax.Array`` (read-only snapshot)."""
        return self._data

    @classmethod
    def from_jax(cls, arr, ctx: Optional[Context] = None) -> "NDArray":
        return cls(arr, ctx=ctx)

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # -- dtype / device movement -----------------------------------------
    def astype(self, dtype, copy=True):
        if np.dtype(dtype) == self.dtype and not copy:
            return self
        return invoke(get_op("cast"), [self], dtype=np.dtype(dtype).name)

    def copy(self) -> "NDArray":
        return self.copyto(self._ctx)

    def copyto(self, other) -> "NDArray":
        if isinstance(other, NDArray):
            if other is self:
                raise MXNetError("copyto: source and target are the same")
            moved = _jax().device_put(self._data, other._ctx.device)
            other._set_data(moved.astype(other.dtype))
            return other
        assert isinstance(other, Context)
        return NDArray(_jax().device_put(self._data, other.device), ctx=other)

    def as_in_context(self, context: Context) -> "NDArray":
        if context == self._ctx:
            return self
        return self.copyto(context)

    as_in_ctx = as_in_context

    def detach(self) -> "NDArray":
        # share the buffer slot (reference detach shares the chunk): a
        # whole-array view, so later mutations of the base stay visible
        return NDArray(None, _base=self, _index=())

    # -- shape sugar ------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.pop("shape", shape)
        return invoke(get_op("reshape"), [self], shape=tuple(shape), **kwargs)

    def flatten(self):
        return invoke(get_op("flatten"), [self])

    def expand_dims(self, axis):
        return invoke(get_op("expand_dims"), [self], axis=axis)

    def squeeze(self, axis=None):
        return invoke(get_op("squeeze"), [self], axis=axis)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return invoke(get_op("transpose"), [self], axes=axes)

    def swapaxes(self, dim1, dim2):
        return invoke(get_op("swapaxes"), [self], dim1=dim1, dim2=dim2)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke(get_op("split"), [self], num_outputs=num_outputs,
                      axis=axis, squeeze_axis=squeeze_axis)

    def slice_axis(self, axis, begin, end):
        return invoke(get_op("slice_axis"), [self], axis=axis, begin=begin,
                      end=end)

    def take(self, indices, axis=0, mode="clip"):
        return invoke(get_op("take"), [self, _coerce(indices, self)],
                      axis=axis, mode=mode)

    def tile(self, reps):
        return invoke(get_op("tile"), [self], reps=tuple(reps))

    def broadcast_to(self, shape):
        return invoke(get_op("broadcast_to"), [self], shape=tuple(shape))

    def broadcast_like(self, other):
        return invoke(get_op("broadcast_like"), [self, other])

    # -- reductions sugar -------------------------------------------------
    def sum(self, axis=None, keepdims=False):
        return invoke(get_op("sum"), [self], axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return invoke(get_op("mean"), [self], axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return invoke(get_op("max"), [self], axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return invoke(get_op("min"), [self], axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return invoke(get_op("prod"), [self], axis=axis, keepdims=keepdims)

    def argmax(self, axis=None):
        return invoke(get_op("argmax"), [self], axis=axis)

    def argmin(self, axis=None):
        return invoke(get_op("argmin"), [self], axis=axis)

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke(get_op("norm"), [self], ord=ord, axis=axis,
                      keepdims=keepdims)

    def abs(self):
        return invoke(get_op("abs"), [self])

    def sqrt(self):
        return invoke(get_op("sqrt"), [self])

    def square(self):
        return invoke(get_op("square"), [self])

    def exp(self):
        return invoke(get_op("exp"), [self])

    def log(self):
        return invoke(get_op("log"), [self])

    def clip(self, a_min, a_max):
        return invoke(get_op("clip"), [self], a_min=a_min, a_max=a_max)

    def sigmoid(self):
        return invoke(get_op("sigmoid"), [self])

    def tanh(self):
        return invoke(get_op("tanh"), [self])

    def relu(self):
        return invoke(get_op("relu"), [self])

    def softmax(self, axis=-1):
        return invoke(get_op("softmax"), [self], axis=axis)

    def dot(self, other, transpose_a=False, transpose_b=False):
        return invoke(get_op("dot"), [self, other], transpose_a=transpose_a,
                      transpose_b=transpose_b)

    def zeros_like(self):
        return invoke(get_op("zeros_like"), [self])

    def ones_like(self):
        return invoke(get_op("ones_like"), [self])

    def one_hot(self, depth, on_value=1.0, off_value=0.0, dtype="float32"):
        return invoke(get_op("one_hot"), [self], depth=depth,
                      on_value=on_value, off_value=off_value, dtype=dtype)

    # -- autograd ---------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate gradient buffer; marks this array as an autograd leaf.

        Parity: ``NDArray.attach_grad`` / ``MXAutogradMarkVariables``.
        ``stype="row_sparse"`` types the grad buffer so optimizers take
        the lazy (touched-rows-only) update path, as the reference does
        for ``row_sparse`` gradient storage.
        """
        from .. import autograd
        self.grad_req = grad_req
        if stype == "row_sparse":
            from .sparse import RowSparseNDArray
            self._grad = RowSparseNDArray(
                _jnp().zeros(self.shape, self.dtype), ctx=self._ctx)
        else:
            self._grad = NDArray(_jnp().zeros(self.shape, self.dtype),
                                 ctx=self._ctx)
        self._grad._buf = _jax().device_put(self._grad._buf,
                                            self._ctx.device)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # -- indexing ---------------------------------------------------------
    def _norm_key(self, key):
        if isinstance(key, NDArray):
            return key._data.astype("int32")
        if isinstance(key, tuple):
            return tuple(k._data.astype("int32") if isinstance(k, NDArray)
                         else k for k in key)
        return key

    def __getitem__(self, key):
        from .. import autograd
        key = self._norm_key(key)
        def _is_basic(k):
            return isinstance(k, (int, np.integer, builtins.slice)) or \
                k is Ellipsis or k is None
        basic = _is_basic(key) if not isinstance(key, tuple) else \
            all(_is_basic(k) for k in key)
        if basic and autograd.is_recording():
            # recording: slice must live ON the tape — a view would
            # silently produce zero gradients for the base array
            ks = key if isinstance(key, tuple) else (key,)
            enc = []
            for k in ks:
                if isinstance(k, builtins.slice):
                    enc.append(("s", k.start, k.stop, k.step))
                elif k is Ellipsis:
                    enc.append(("e",))
                elif k is None:
                    enc.append(("n",))
                else:
                    enc.append(("i", int(k)))
            return invoke(get_op("_slice_basic"), [self],
                          key=tuple(enc))
        if basic:
            # basic indexing → view sharing this buffer slot
            return NDArray(None, _base=self, _index=key)
        if autograd.is_recording():
            raise MXNetError(
                "advanced indexing is not differentiable on the tape; "
                "use take/gather_nd/pick inside autograd.record()")
        # advanced indexing → copy (same as reference)
        out = self._data[key]
        return NDArray(out, ctx=self._ctx)

    def __setitem__(self, key, value):
        from .. import autograd
        if autograd.is_recording():
            raise MXNetError(
                "In-place assignment is not supported inside "
                "autograd.record() — parity with reference semantics.")
        key = self._norm_key(key)
        jnp = _jnp()
        if isinstance(value, NDArray):
            val = value._data
        elif isinstance(value, numeric_types):
            self._set_data(self._data.at[key].set(
                np.asarray(value).astype(self.dtype)))
            return
        else:
            val = jnp.asarray(value, dtype=self.dtype)
        self._set_data(self._data.at[key].set(val.astype(self.dtype)))

    # -- arithmetic operators --------------------------------------------
    def _binary(self, other, opname, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return invoke(get_op(opname), [a, b])
        if isinstance(other, numeric_types):
            return invoke(get_op(scalar_op), [self], scalar=other)
        if isinstance(other, np.ndarray):
            o = array(other, ctx=self._ctx, dtype=other.dtype)
            a, b = (o, self) if reverse else (self, o)
            return invoke(get_op(opname), [a, b])
        return NotImplemented

    def __add__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        if isinstance(o, numeric_types):
            return invoke(get_op("_rminus_scalar"), [self], scalar=o)
        return self._binary(o, "broadcast_sub", "_minus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, o):
        if isinstance(o, numeric_types):
            return invoke(get_op("_rdiv_scalar"), [self], scalar=o)
        return self._binary(o, "broadcast_div", "_div_scalar", reverse=True)

    def __mod__(self, o):
        return self._binary(o, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, o):
        if isinstance(o, numeric_types):
            return invoke(get_op("_rmod_scalar"), [self], scalar=o)
        return self._binary(o, "broadcast_mod", "_mod_scalar", reverse=True)

    def __pow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        if isinstance(o, numeric_types):
            return invoke(get_op("_rpower_scalar"), [self], scalar=o)
        return NotImplemented

    def __neg__(self):
        return invoke(get_op("negative"), [self])

    def __abs__(self):
        return invoke(get_op("abs"), [self])

    def __eq__(self, o):
        if o is None:
            return False
        return self._binary(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binary(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal",
                            "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal",
                            "_lesser_equal_scalar")

    __hash__ = object.__hash__

    def _inplace(self, other, opname, scalar_op):
        from .. import autograd
        if autograd.is_recording():
            raise MXNetError("In-place operations are not supported when "
                             "recording with autograd.")
        res = self._binary(other, opname, scalar_op)
        self._set_data(res._data.astype(self.dtype))
        return self

    def __iadd__(self, o):
        return self._inplace(o, "broadcast_add", "_plus_scalar")

    def __isub__(self, o):
        return self._inplace(o, "broadcast_sub", "_minus_scalar")

    def __imul__(self, o):
        return self._inplace(o, "broadcast_mul", "_mul_scalar")

    def __itruediv__(self, o):
        return self._inplace(o, "broadcast_div", "_div_scalar")


# ---------------------------------------------------------------------------
# imperative invoke — the MXImperativeInvokeEx equivalent
# ---------------------------------------------------------------------------


def _coerce(x, like: NDArray) -> NDArray:
    if isinstance(x, NDArray):
        return x
    return array(np.asarray(x), ctx=like._ctx)


# active closure-capture scope (contrib control-flow ops detect which
# external NDArrays a body closure touches — see ndarray/contrib.py)
_capture_scope = None

# autograd resolved once (a per-call `from .. import` costs ~2 us on
# the dispatch hot path); deferred because of the import cycle
_autograd = None


def invoke(op: OpDef, inputs: Sequence[NDArray], out=None,
           ctx: Optional[Context] = None, **kwargs):
    """Execute op imperatively: the hot path (SURVEY.md §3.1).

    Python → compile-cache lookup → PJRT async execute → NDArray handle(s)
    returned immediately; sync happens at wait_to_read/asnumpy.
    """
    global _autograd
    autograd = _autograd
    if autograd is None:
        from .. import autograd as _ag
        autograd = _autograd = _ag

    if _capture_scope is not None:
        _capture_scope.observe(inputs)

    if inputs:
        ctx = inputs[0]._ctx
        arrays = [i._data for i in inputs]
    else:
        ctx = ctx or current_context()
        arrays = []

    # dynamic scalar attrs ride as 0-d input arrays (no recompile on change)
    scalar_vals = []
    if op.scalar_attrs and any(s in kwargs for s in op.scalar_attrs):
        ref = op.scalar_ref_input
        ref_dtype = (inputs[ref].dtype if ref is not None and inputs
                     else np.dtype("float32"))
        sdt = ref_dtype if ref_dtype.name in _FLOAT_DTYPES \
            else np.dtype("float32")
        # scalars bind POSITIONALLY after the tensor inputs, so once
        # any is supplied EVERY one must be materialized — an omitted
        # earlier scalar would silently shift later values into the
        # wrong parameter (e.g. t binding as wd)
        for sname in op.scalar_attrs:
            if sname in kwargs:
                v = kwargs.pop(sname)
            elif sname in op.scalar_defaults:
                v = op.scalar_defaults[sname]
            else:
                raise MXNetError(
                    f"{op.name}: scalar attr {sname!r} is required "
                    f"when any of {op.scalar_attrs} is given")
            if isinstance(v, NDArray):
                scalar_vals.append(v._data)
            else:
                dt = sdt
                if isinstance(v, (int, np.integer)) and \
                        not isinstance(v, (bool, np.bool_)) and \
                        ref_dtype.kind in "iu":
                    dt = ref_dtype
                scalar_vals.append(np.asarray(v, dtype=dt))

    all_arrays = arrays + scalar_vals

    if autograd.is_recording():
        if out is not None:
            raise MXNetError("`out` is not supported when recording "
                             "with autograd.")
        node, outputs_data = autograd._record_op(op, kwargs, all_arrays,
                                                 inputs)
        return _wrap_outputs(op, outputs_data, ctx, node)

    if op.wrap_ctx or not inputs:
        with _jax().default_device(ctx.device):
            outputs_data = engine.invoke_compiled(op.name, op.fcompute,
                                                  kwargs, *all_arrays)
    else:
        outputs_data = engine.invoke_compiled(op.name, op.fcompute, kwargs,
                                              *all_arrays)

    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        data = outputs_data if isinstance(outputs_data, tuple) \
            else (outputs_data,)
        for o, d in zip(outs, data):
            o._set_data(d.astype(o.dtype) if o.dtype != d.dtype else d)
        return out
    return _wrap_outputs(op, outputs_data, ctx, None)


def _wrap_outputs(op: OpDef, outputs_data, ctx, node):
    if isinstance(outputs_data, tuple) and op.num_outputs != 1:
        outs = []
        for i, d in enumerate(outputs_data):
            o = NDArray(d, ctx=ctx)
            if node is not None:
                o._ag_node = node
                o._ag_out_idx = i
            outs.append(o)
        if node is not None:
            node.outputs = [o for o in outs]
        if _capture_scope is not None:
            _capture_scope.mark_internal(outs)
        return outs
    o = NDArray(outputs_data, ctx=ctx)
    if node is not None:
        o._ag_node = node
        o._ag_out_idx = 0
        node.outputs = [o]
    if _capture_scope is not None:
        _capture_scope.mark_internal([o])
    return o


# ---------------------------------------------------------------------------
# creation / io
# ---------------------------------------------------------------------------


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """Create an NDArray from array-like (parity: mx.nd.array)."""
    ctx = ctx or current_context()
    was_ndarray = isinstance(source, (np.ndarray, NDArray))
    if isinstance(source, NDArray):
        src = source.asnumpy()
    else:
        src = np.asarray(source)
    if dtype is None:
        if not was_ndarray:
            # python lists/scalars default to float32 (MXNet rule)
            dtype = "float32"
        elif src.dtype == np.float64:
            dtype = "float32"
        else:
            dtype = src.dtype
    arr = _jax().device_put(np.asarray(src, dtype=dtype), ctx.device)
    return NDArray(arr, ctx=ctx)


def empty(shape, ctx=None, dtype="float32") -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype="float32", **kwargs) -> NDArray:
    shape = (shape,) if isinstance(shape, (int, np.integer)) else tuple(shape)
    return invoke(get_op("_zeros"), [], ctx=ctx, shape=shape,
                  dtype=np.dtype(dtype).name)


def ones(shape, ctx=None, dtype="float32", **kwargs) -> NDArray:
    shape = (shape,) if isinstance(shape, (int, np.integer)) else tuple(shape)
    return invoke(get_op("_ones"), [], ctx=ctx, shape=shape,
                  dtype=np.dtype(dtype).name)


def full(shape, val, ctx=None, dtype="float32") -> NDArray:
    shape = (shape,) if isinstance(shape, (int, np.integer)) else tuple(shape)
    return invoke(get_op("_full"), [], ctx=ctx, shape=shape, value=float(val),
                  dtype=np.dtype(dtype).name)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None,
           dtype="float32") -> NDArray:
    return invoke(get_op("_arange"), [], ctx=ctx, start=start, stop=stop,
                  step=step, repeat=repeat, dtype=np.dtype(dtype).name)


def eye(N, M=0, k=0, ctx=None, dtype="float32") -> NDArray:
    return invoke(get_op("_eye"), [], ctx=ctx, N=N, M=M, k=k,
                  dtype=np.dtype(dtype).name)


def moveaxis(data, source, destination):
    axes = list(range(data.ndim))
    axes.remove(source % data.ndim)
    axes.insert(destination % data.ndim, source % data.ndim)
    return data.transpose(tuple(axes))


def concatenate(arrays, axis=0):
    return invoke(get_op("concat"), list(arrays), dim=axis)


def waitall():
    engine.waitall()


# ---------------------------------------------------------------------------
# serialization — API parity with mx.nd.save/load (reference ndarray.cc
# Save/Load).  The native WRITER uses the self-described MXTPU001
# layout (magic, count, names, then per array: dtype/shape header + raw
# little-endian bytes); the LOADER additionally falls back to
# legacy_io for reference-written dmlc::Stream .params files, so
# upstream checkpoints load read-only (files written here are not
# readable by the reference).
# ---------------------------------------------------------------------------

_MAGIC = b"MXTPU001"


def save(fname: str, data):
    if isinstance(data, NDArray):
        pairs = [("", data)]
    elif isinstance(data, dict):
        pairs = list(data.items())
    else:
        pairs = [("", d) for d in data]
    if fname.endswith(".safetensors"):
        # ecosystem interop by extension: any {name: NDArray} dict
        # round-trips with HF tooling (unnamed entries get list
        # indices, matching torch.save-style exports).  A saved LIST is
        # marked in __metadata__ so load() can reconstruct it without
        # guessing from key patterns — a foreign or explicit dict with
        # digit keys must stay a dict.
        from ..models.hf_loader import write_safetensors
        was_list = not isinstance(data, (NDArray, dict))
        named = {}
        for i, (name, arr) in enumerate(pairs):
            key = name or str(i)
            if key in named:
                raise MXNetError(
                    f"save: duplicate tensor name {key!r} after "
                    "index substitution — a tensor would be "
                    "silently dropped")
            named[key] = arr.asnumpy()
        write_safetensors(fname, named,
                          metadata={"mxtpu_format": "list"}
                          if was_list else None)
        return
    with open(fname, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<q", len(pairs)))
        for name, arr in pairs:
            a = arr.asnumpy()
            nb = name.encode()
            hdr = json.dumps({"dtype": a.dtype.name,
                              "shape": list(a.shape)}).encode()
            f.write(struct.pack("<q", len(nb)))
            f.write(nb)
            f.write(struct.pack("<q", len(hdr)))
            f.write(hdr)
            raw = np.ascontiguousarray(a).tobytes()
            f.write(struct.pack("<q", len(raw)))
            f.write(raw)


def _load_stream(f, what: str):
    magic = f.read(8)
    if magic != _MAGIC:
        from . import legacy_io
        if legacy_io.looks_legacy(magic):
            # reference-written .params / nd.save checkpoint
            # (dmlc::Stream layout) — read-only interop
            f.seek(0)
            names, arrays = legacy_io.load_legacy(f)
            nds = [array(a, dtype=a.dtype) for a in arrays]
            if names:
                return dict(zip(names, nds))
            return nds
        raise MXNetError(f"{what}: not an NDArray file")
    n = struct.unpack("<q", f.read(8))[0]
    named = {}
    unnamed = []
    any_named = False
    for _ in range(n):
        ln = struct.unpack("<q", f.read(8))[0]
        name = f.read(ln).decode()
        lh = struct.unpack("<q", f.read(8))[0]
        hdr = json.loads(f.read(lh).decode())
        lr = struct.unpack("<q", f.read(8))[0]
        raw = f.read(lr)
        a = np.frombuffer(raw, dtype=hdr["dtype"]).reshape(hdr["shape"])
        nd = array(a, dtype=a.dtype)
        if name:
            any_named = True
            named[name] = nd
        else:
            unnamed.append(nd)
    return named if any_named else unnamed


def load(fname: str):
    if fname.endswith(".safetensors"):
        # sniff first: a native/legacy checkpoint misnamed
        # .safetensors keeps the native loader's error contract
        with open(fname, "rb") as f:
            magic = f.read(8)
        if magic != _MAGIC:
            from ..models.hf_loader import read_safetensors
            raw, meta = read_safetensors(fname, return_metadata=True)
            loaded = {name: array(np.asarray(a), dtype=a.dtype)
                      for name, a in raw.items()}
            # save(list) stores unnamed entries under keys "0","1",...
            # (the safetensors format has no list notion) and stamps
            # __metadata__; reconstruct the list only on that marker so
            # the documented round-trip holds (ADVICE r4) while foreign
            # or explicit digit-keyed dicts stay dicts
            if meta.get("mxtpu_format") == "list":
                try:
                    idx = sorted(int(k) for k in loaded)
                except ValueError:
                    return loaded
                if idx == list(range(len(loaded))):
                    return [loaded[str(i)] for i in idx]
            return loaded
    with open(fname, "rb") as f:
        return _load_stream(f, fname)


def load_buffer(buf: bytes):
    """Deserialize from in-memory bytes (parity:
    MXNDArrayLoadFromBuffer — the C predict API's param-blob path)."""
    import io
    return _load_stream(io.BytesIO(buf), "<buffer>")
