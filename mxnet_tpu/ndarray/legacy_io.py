"""Reader for the reference's dmlc::Stream NDArray file format.

``mx.nd.save`` in upstream MXNet (``src/ndarray/ndarray.cc``
``NDArray::Save/Load`` + ``MXNDArrayLoad``) writes:

    uint64  kMXAPINDArrayListMagic = 0x112
    uint64  reserved = 0
    uint64  ndarray count                     (dmlc vector header)
    per array:
        uint32  magic: V1 0xF993FAC8 | V2 0xF993FAC9 | V3 0xF993FACA
        int32   storage type                  (V2/V3 only; 0 = dense)
        shape:  uint32 ndim + ndim x uint32   (V1/V2)
                uint32 ndim + ndim x int64    (V3 — int64 tensor size)
        int32   dev_type, int32 dev_id        (Context::Load)
        int32   type_flag                     (mshadow dtype enum)
        raw     little-endian data bytes      (size * dtype itemsize)
    uint64  name count                        (dmlc vector header)
    per name: uint64 length + utf-8 bytes

This module parses that layout READ-ONLY so reference-written
``.params`` / ``nd.save`` checkpoints load directly (VERDICT r2 next
#9); the rebuild's own writer keeps its self-described MXTPU001 layout.
float64 payloads parse exactly but materialize under the framework's
x64 policy (f32 unless MXTPU_ENABLE_X64 is set), like every other f64
source.
The reference mount is empty this round, so the layout above is
reconstructed from the upstream sources' documented behavior and
guarded by hand-built fixture tests (tests/test_ndarray.py).
"""
from __future__ import annotations

import struct

import numpy as np

from ..base import MXNetError

LIST_MAGIC = 0x112
_V1 = 0xF993FAC8
_V2 = 0xF993FAC9
_V3 = 0xF993FACA

# mshadow type_flag enum (mshadow/base.h)
_TYPE_FLAGS = {0: np.float32, 1: np.float64, 2: np.float16,
               3: np.uint8, 4: np.int32, 5: np.int8, 6: np.int64,
               7: np.bool_}


def looks_legacy(head8: bytes) -> bool:
    """True if the first 8 bytes are the dmlc list magic."""
    return len(head8) == 8 and \
        struct.unpack("<Q", head8)[0] == LIST_MAGIC


def _read(f, n, what):
    b = f.read(n)
    if len(b) != n:
        raise MXNetError(
            f"legacy NDArray file truncated while reading {what} "
            f"(wanted {n} bytes, got {len(b)})")
    return b


def _load_one(f):
    (magic,) = struct.unpack("<I", _read(f, 4, "ndarray magic"))
    if magic not in (_V1, _V2, _V3):
        raise MXNetError(
            f"bad NDArray magic 0x{magic:08x} (expected the dmlc "
            "V1/V2/V3 save format)")
    if magic in (_V2, _V3):
        (stype,) = struct.unpack("<i", _read(f, 4, "storage type"))
        if stype != 0:
            raise MXNetError(
                f"legacy load: sparse storage type {stype} is not "
                "supported (dense checkpoints only)")
    (ndim,) = struct.unpack("<I", _read(f, 4, "ndim"))
    if ndim > 32:
        raise MXNetError(f"implausible ndim {ndim} in legacy file")
    dim_fmt, dim_sz = ("<q", 8) if magic == _V3 else ("<I", 4)
    shape = tuple(
        struct.unpack(dim_fmt, _read(f, dim_sz, "shape dim"))[0]
        for _ in range(ndim))
    # Context (dev_type, dev_id) — load always lands on our default ctx
    struct.unpack("<ii", _read(f, 8, "context"))
    (type_flag,) = struct.unpack("<i", _read(f, 4, "type flag"))
    dt = _TYPE_FLAGS.get(type_flag)
    if dt is None:
        raise MXNetError(f"unknown type_flag {type_flag} in legacy "
                         "NDArray file")
    dt = np.dtype(dt)
    n_elem = 1
    for d in shape:
        n_elem *= int(d)
    raw = _read(f, n_elem * dt.itemsize, "tensor data")
    return np.frombuffer(raw, dtype=dt).reshape(shape)


def load_legacy(f):
    """Parse an open binary stream positioned at 0.

    Returns ``(names, arrays)`` — names is ``[]`` when the file was
    saved from a list (empty name vector)."""
    head = struct.unpack("<QQ", _read(f, 16, "file header"))
    if head[0] != LIST_MAGIC:
        raise MXNetError("not a legacy dmlc NDArray file")
    (count,) = struct.unpack("<Q", _read(f, 8, "ndarray count"))
    if count > 1_000_000:
        raise MXNetError(f"implausible ndarray count {count}")
    arrays = [_load_one(f) for _ in range(count)]
    (n_names,) = struct.unpack("<Q", _read(f, 8, "name count"))
    if n_names not in (0, count):
        raise MXNetError(
            f"legacy file has {n_names} names for {count} arrays")
    names = []
    for _ in range(n_names):
        (ln,) = struct.unpack("<Q", _read(f, 8, "name length"))
        names.append(_read(f, ln, "name").decode("utf-8"))
    return names, arrays
