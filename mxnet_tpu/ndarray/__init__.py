"""``mx.nd`` namespace: NDArray + every registered operator as a function.

Capability parity: reference ``python/mxnet/ndarray/`` — the reference
codegens ``gen_op`` stubs at import from the C op registry
(``_init_op_module``); here we generate wrappers from the Python op registry
the same way.  Convention mirrored from the reference: tensor arguments are
the leading positional args (NDArrays), operator attributes follow
positionally (in declaration order) or as keywords; every op accepts
``out=``.
"""
from __future__ import annotations

import sys

import numpy as np

from ..base import MXNetError
from ..ops.registry import get_op, list_ops, OpDef
from .ndarray import (NDArray, invoke, array, empty, zeros, ones, full,
                      arange, eye, concatenate, save, load, load_buffer, waitall,
                      moveaxis)

_mod = sys.modules[__name__]


def _make_wrapper(opname: str, op: OpDef):
    ordered_attrs = tuple(op.scalar_attrs) + tuple(op.attr_names)

    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        ctx = kwargs.pop("ctx", None)
        kwargs.pop("name", None)
        inputs = []
        attr_pos = []
        for a in args:
            if isinstance(a, NDArray):
                inputs.append(a)
            else:
                attr_pos.append(a)
        for name, val in zip(ordered_attrs, attr_pos):
            if name in kwargs:
                raise TypeError(f"{opname}: got multiple values for {name}")
            kwargs[name] = val
        if len(attr_pos) > len(ordered_attrs):
            raise TypeError(f"{opname}: too many positional arguments")
        return invoke(op, inputs, out=out, ctx=ctx, **kwargs)

    fn.__name__ = opname
    fn.__qualname__ = opname
    fn.__doc__ = op.doc
    return fn


def _generate(target_mod):
    for opname in list_ops():
        if opname in _CUSTOM:
            setattr(target_mod, opname, _CUSTOM[opname])
            continue
        op = get_op(opname)
        setattr(target_mod, opname, _make_wrapper(opname, op))


# ---------------------------------------------------------------------------
# ops that need frontend logic (RNG keys, aux-state mutation, mode flags)
# ---------------------------------------------------------------------------


def Dropout(data, p=0.5, mode="training", axes=(), **kwargs):
    """Parity: nd.Dropout. RNG key threaded from mx.random's state."""
    from .. import autograd
    from .. import random as _rnd
    training = autograd.is_training() or mode == "always"
    if not training or p <= 0.0:
        return invoke(get_op("identity"), [data])
    key = _rnd._next_key_nd(data.context)
    return invoke(get_op("Dropout"), [data, key], p=p, mode=mode,
                  axes=tuple(axes), training=True)


def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
              momentum=0.9, fix_gamma=True, use_global_stats=False,
              output_mean_var=False, axis=1, **kwargs):
    """Parity: nd.BatchNorm incl. aux-state (moving stats) update."""
    from .. import autograd
    training = autograd.is_training() and not use_global_stats
    outs = invoke(get_op("BatchNorm"),
                  [data, gamma, beta, moving_mean, moving_var],
                  eps=eps, momentum=momentum, fix_gamma=fix_gamma,
                  use_global_stats=use_global_stats,
                  output_mean_var=output_mean_var, axis=axis,
                  training=training)
    out, batch_mean, batch_var = outs
    if training:
        # aux-state update, outside the tape (reference updates aux arrays
        # without recording them)
        m = momentum
        moving_mean._set_data(m * moving_mean._data
                              + (1.0 - m) * batch_mean._data)
        moving_var._set_data(m * moving_var._data
                             + (1.0 - m) * batch_var._data)
    if output_mean_var:
        return out, batch_mean, batch_var
    return out


def RNN(*args, **kwargs):
    raise NotImplementedError(
        "nd.RNN: use mx.gluon.rnn layers (scan-lowered); the packed-weight "
        "fused op surface lands with the RNN milestone")


def maximum(lhs, rhs, out=None):
    """Parity: nd.maximum — scalar or array operands."""
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return invoke(get_op("broadcast_maximum"), [lhs, rhs], out=out)
    if isinstance(lhs, NDArray):
        return invoke(get_op("_maximum_scalar"), [lhs], scalar=rhs, out=out)
    if isinstance(rhs, NDArray):
        return invoke(get_op("_maximum_scalar"), [rhs], scalar=lhs, out=out)
    return builtins_max(lhs, rhs)


def minimum(lhs, rhs, out=None):
    """Parity: nd.minimum — scalar or array operands."""
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return invoke(get_op("broadcast_minimum"), [lhs, rhs], out=out)
    if isinstance(lhs, NDArray):
        return invoke(get_op("_minimum_scalar"), [lhs], scalar=rhs, out=out)
    if isinstance(rhs, NDArray):
        return invoke(get_op("_minimum_scalar"), [rhs], scalar=lhs, out=out)
    return builtins_min(lhs, rhs)


builtins_max = max
builtins_min = min

_CUSTOM = {"Dropout": Dropout, "BatchNorm": BatchNorm, "RNN": RNN,
           "maximum": maximum, "minimum": minimum}

_generate(_mod)

from . import random  # noqa: E402  (nd.random namespace)
from . import sparse  # noqa: E402  (stype facade)
from . import contrib  # noqa: E402  (control-flow ops)

__all__ = ["NDArray", "array", "empty", "zeros", "ones", "full", "arange",
           "eye", "concatenate", "save", "load", "load_buffer", "waitall", "invoke",
           "random", "sparse", "contrib", "moveaxis"]
