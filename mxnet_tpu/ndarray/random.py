"""``mx.nd.random`` namespace (parity: python/mxnet/ndarray/random.py).

Same entry points as ``mx.random``, re-exported under nd.
"""
from ..random import (uniform, normal, randn, randint, exponential, gamma,
                      poisson, multinomial, shuffle, bernoulli)

__all__ = ["uniform", "normal", "randn", "randint", "exponential", "gamma",
           "poisson", "multinomial", "shuffle", "bernoulli"]
