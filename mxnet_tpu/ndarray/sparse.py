"""Sparse NDArrays (parity: python/mxnet/ndarray/sparse.py).

Capability note (SURVEY.md §7 P6): the reference supports ``row_sparse``
and ``csr`` storage types end-to-end.  TPU/XLA has no sparse buffer
type; the rebuild's answer has two tiers:

* **csr built from (data, indices, indptr)** stores ONLY the compressed
  arrays on device — no dense buffer exists until a generic op touches
  the array (lazy densification), and :func:`dot` computes on the nnz
  values via a scatter-add (XLA segment-sum lowering).  A 100k x 100k
  matrix with 1k nonzeros costs kilobytes, not 40 GB.
* **everything else** (dense-built sparse arrays, generic ops on any
  sparse array) runs on dense buffers with stype metadata — numerics
  identical, memory dense, documented in docs/capability_gaps.md.

row_sparse keeps real LAZY-UPDATE semantics in the optimizers (only
touched rows advance state) over dense storage.
"""
from __future__ import annotations

from functools import partial

import numpy as np

from ..base import MXNetError
from .ndarray import NDArray, array

__all__ = ["CSRNDArray", "RowSparseNDArray", "csr_matrix",
           "row_sparse_array", "zeros", "dot", "retain"]


def _jnp():
    import jax.numpy as jnp
    return jnp


def _csr_rows(iptr, nnz):
    jnp = _jnp()
    return jnp.searchsorted(iptr, jnp.arange(nnz), side="right") - 1


def _densify_csr(vals, idx, iptr, shape):
    jnp = _jnp()
    rows = _csr_rows(iptr, vals.shape[0])
    # .add, not .set: duplicate (row, col) entries SUM (scipy/reference
    # semantics), and the dot path must agree with the densified path
    return jnp.zeros(shape, vals.dtype).at[rows, idx].add(vals)


class _SparseFacade(NDArray):
    """Common lazy-compressed machinery: subclasses store their
    compressed parts in ``_parts`` (+ ``_parts_shape`` metadata) and
    implement ``_densify()``; a dense buffer materializes only when a
    generic op touches ``_data``."""

    __slots__ = ("_parts",)
    _stype = "default"

    def __init__(self, data, ctx=None, _base=None, _index=None):
        super().__init__(data, ctx=ctx, _base=_base, _index=_index)
        self._parts = None

    def _densify(self):  # pragma: no cover - overridden when used
        raise NotImplementedError

    @property
    def _data(self):
        # generic ops densify LAZILY; sparse-aware paths (dot/retain,
        # the compressed-part properties) never come through here
        if self._buf is None and self._base is None and \
                self._parts is not None:
            self._buf = self._densify()
        return NDArray._data.fget(self)

    def _set_data(self, new):
        self._parts = None   # a dense mutation invalidates the parts
        NDArray._set_data(self, new)

    @property
    def is_compressed(self):
        """True while no dense buffer has been materialized."""
        return self._buf is None and self._parts is not None

    @property
    def shape(self):
        if self.is_compressed:
            return tuple(self._parts[-1])
        return NDArray.shape.fget(self)

    @property
    def dtype(self):
        if self.is_compressed:
            return self._parts[0].dtype
        return NDArray.dtype.fget(self)

    @property
    def ndim(self):
        if self.is_compressed:
            return len(self._parts[-1])
        return NDArray.ndim.fget(self)

    @property
    def stype(self):
        return self._stype

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, ctx=self._ctx)
        return _make(stype, self._data, self._ctx)


class CSRNDArray(_SparseFacade):
    __slots__ = ()
    _stype = "csr"
    # _parts = (vals, indices, indptr, shape) when compressed

    @property
    def _csr(self):   # sparse-aware callers (dot) read this
        return self._parts

    def _densify(self):
        return _densify_csr(*self._parts)

    @property
    def indices(self):
        if self._parts is not None:
            # already on device: wrap, don't round-trip via host
            return NDArray(self._parts[1].astype(_jnp().int64),
                           ctx=self._ctx)
        a = self.asnumpy()
        return array(np.nonzero(a)[1].astype("int64"), ctx=self._ctx,
                     dtype="int64")

    @property
    def indptr(self):
        if self._parts is not None:
            return NDArray(self._parts[2].astype(_jnp().int64),
                           ctx=self._ctx)
        a = self.asnumpy()
        counts = (a != 0).sum(axis=1)
        return array(np.concatenate([[0], np.cumsum(counts)])
                     .astype("int64"), ctx=self._ctx, dtype="int64")

    @property
    def data(self):
        if self._parts is not None:
            return NDArray(self._parts[0], ctx=self._ctx)
        a = self.asnumpy()
        return array(a[a != 0], ctx=self._ctx)


class RowSparseNDArray(_SparseFacade):
    __slots__ = ()
    _stype = "row_sparse"
    # _parts = (row values, row indices, shape) when compressed

    @property
    def _rsp(self):   # sparse-aware callers (retain) read this
        return self._parts

    def _densify(self):
        vals, idx, shape = self._parts
        return _jnp().zeros(shape, vals.dtype).at[idx].set(vals)

    @property
    def indices(self):
        if self._parts is not None:
            return NDArray(self._parts[1].astype(_jnp().int64),
                           ctx=self._ctx)
        a = self.asnumpy()
        nz = np.nonzero(np.any(a != 0, axis=tuple(range(1, a.ndim))))[0]
        return array(nz.astype("int64"), ctx=self._ctx, dtype="int64")

    @property
    def data(self):
        if self._parts is not None:
            return NDArray(self._parts[0], ctx=self._ctx)
        a = self.asnumpy()
        nz = np.nonzero(np.any(a != 0, axis=tuple(range(1, a.ndim))))[0]
        return array(a[nz], ctx=self._ctx)


def _make(stype, data, ctx):
    cls = {"csr": CSRNDArray, "row_sparse": RowSparseNDArray}[stype]
    out = cls(data, ctx=ctx)
    return out


def csr_matrix(arg1, shape=None, ctx=None, dtype="float32"):
    if isinstance(arg1, (list, np.ndarray, NDArray)):
        base = array(arg1, ctx=ctx, dtype=dtype)
        return _make("csr", base._data, base._ctx)
    # (data, indices, indptr): store ONLY the compressed parts — the
    # dense buffer appears lazily if a generic op ever needs it
    data, indices, indptr = arg1
    if shape is None:
        raise MXNetError("csr_matrix from (data, indices, indptr) "
                         "requires shape=")
    jnp = _jnp()
    vals_np = np.asarray(data, dtype=dtype)
    idx_np = np.asarray(indices, dtype="int32")
    iptr_np = np.asarray(indptr, dtype="int32")
    if iptr_np.shape[0] != int(shape[0]) + 1:
        raise MXNetError(
            f"indptr length {iptr_np.shape[0]} != shape[0]+1 "
            f"({int(shape[0]) + 1})")
    # malformed structure must fail HERE: jax scatter silently drops
    # out-of-bounds updates and gather clamps, so bad csr parts would
    # otherwise produce quietly wrong numerics
    if iptr_np.size and (iptr_np[0] != 0
                         or iptr_np[-1] != vals_np.size
                         or (np.diff(iptr_np) < 0).any()):
        raise MXNetError(
            f"invalid indptr: must start at 0, end at nnz "
            f"({vals_np.size}) and be non-decreasing")
    if idx_np.size and (idx_np.min() < 0
                        or idx_np.max() >= int(shape[1])):
        raise MXNetError(
            f"column indices out of range for shape {tuple(shape)}")
    if idx_np.shape[0] != vals_np.shape[0]:
        raise MXNetError("data and indices must have equal length")
    vals = jnp.asarray(vals_np)
    idx = jnp.asarray(idx_np)
    iptr = jnp.asarray(iptr_np)
    out = CSRNDArray(None, ctx=ctx)
    out._parts = (vals, idx, iptr, tuple(int(d) for d in shape))
    return out


def row_sparse_array(arg1, shape=None, ctx=None, dtype="float32"):
    if isinstance(arg1, (list, np.ndarray, NDArray)) and shape is None:
        base = array(arg1, ctx=ctx, dtype=dtype)
        return _make("row_sparse", base._data, base._ctx)
    # (data, indices): compressed rows only — the 10M-row embedding
    # gradient with 1k touched rows costs 1k rows of memory
    data, indices = arg1
    if shape is None:
        raise MXNetError("row_sparse_array from (data, indices) "
                         "requires shape=")
    vals_np = np.asarray(data, dtype=dtype)
    idx_np = np.asarray(indices, dtype="int32")
    if vals_np.shape[0] != idx_np.shape[0]:
        raise MXNetError("data and indices must have equal length")
    if vals_np.ndim != len(shape) or \
            vals_np.shape[1:] != tuple(int(d) for d in shape[1:]):
        raise MXNetError(
            f"data shape {vals_np.shape} incompatible with row-sparse "
            f"shape {tuple(shape)} (need (k,) + shape[1:])")
    if idx_np.size and (idx_np.min() < 0
                        or idx_np.max() >= int(shape[0])):
        raise MXNetError(
            f"row indices out of range for shape {tuple(shape)}")
    if idx_np.size > 1 and not (np.diff(idx_np) > 0).all():
        raise MXNetError("row indices must be strictly increasing "
                         "(sorted, unique) — the row_sparse contract")
    jnp = _jnp()
    out = RowSparseNDArray(None, ctx=ctx)
    out._parts = (jnp.asarray(vals_np), jnp.asarray(idx_np),
                  tuple(int(d) for d in shape))
    return out


def retain(data, indices):
    """Keep only the listed rows (parity: ``mx.nd.sparse.retain``).

    On a COMPRESSED row_sparse array the selection runs on the stored
    rows only (host-side index intersection, device gather); anything
    else densifies and masks."""
    keep = np.asarray(
        indices.asnumpy() if isinstance(indices, NDArray) else indices,
        dtype="int64")
    n_rows = int(data.shape[0])
    if keep.size and (keep.min() < 0 or keep.max() >= n_rows):
        raise MXNetError(
            f"retain: indices out of range for {n_rows} rows")
    if isinstance(data, RowSparseNDArray) and data._parts is not None:
        vals, idx, shape = data._parts
        sel = _jnp().asarray(np.nonzero(np.isin(np.asarray(idx),
                                                keep))[0])
        out = RowSparseNDArray(None, ctx=data._ctx)
        out._parts = (vals[sel], idx[sel], shape)
        return out
    a = data.asnumpy().copy()
    mask = np.zeros(a.shape[0], bool)
    mask[keep] = True
    a[~mask] = 0
    base = array(a, ctx=data.context if isinstance(data, NDArray)
                 else None)
    return _make("row_sparse", base._data, base._ctx)


_CSR_DOT = None


def _get_csr_dot():
    global _CSR_DOT
    if _CSR_DOT is None:
        import jax
        jnp = _jnp()

        @partial(jax.jit, static_argnums=(4, 5))
        def f(vals, idx, iptr, rhs, out_rows, transpose):
            rows = _csr_rows(iptr, vals.shape[0])
            if transpose:
                contrib = vals[:, None] * rhs[rows]
                return jnp.zeros((out_rows, rhs.shape[1]),
                                 vals.dtype).at[idx].add(contrib)
            contrib = vals[:, None] * rhs[idx]
            return jnp.zeros((out_rows, rhs.shape[1]),
                             vals.dtype).at[rows].add(contrib)

        _CSR_DOT = f
    return _CSR_DOT


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (parity: ``mx.nd.sparse.dot``).

    A COMPRESSED csr lhs runs a scatter-add over its nnz values only
    (XLA lowers to a segment-sum): FLOPs and intermediate memory scale
    with nnz, never with the dense shape, and the lhs stays
    un-densified.  Anything else — including calls under
    ``autograd.record()``, which must flow through the recorded op so
    gradients exist — falls back to the dense ``dot``."""
    from .. import autograd
    if isinstance(lhs, CSRNDArray) and lhs._csr is not None and \
            isinstance(rhs, NDArray) and \
            not isinstance(rhs, _SparseFacade) and \
            not autograd.is_recording():
        vals, idx, iptr, shape = lhs._csr
        r = rhs._data
        if transpose_b:
            r = r.T
        squeeze = r.ndim == 1
        if squeeze:
            r = r[:, None]
        want = shape[0] if transpose_a else shape[1]
        if int(r.shape[0]) != want:
            raise MXNetError(
                f"sparse.dot: lhs {shape}{'^T' if transpose_a else ''} "
                f"incompatible with rhs {tuple(rhs.shape)}")
        out_rows = shape[1] if transpose_a else shape[0]
        res = _get_csr_dot()(vals, idx, iptr, r, out_rows,
                             bool(transpose_a))
        if squeeze:
            res = res[:, 0]
        return NDArray(res, ctx=lhs._ctx)
    from ..ops.registry import get_op
    from .ndarray import invoke
    return invoke(get_op("dot"), [lhs, rhs], transpose_a=transpose_a,
                  transpose_b=transpose_b)


def zeros(stype, shape, ctx=None, dtype="float32"):
    from .ndarray import zeros as _dense_zeros
    base = _dense_zeros(shape, ctx=ctx, dtype=dtype)
    if stype == "default":
        return base
    return _make(stype, base._data, base._ctx)
