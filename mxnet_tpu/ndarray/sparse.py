"""Sparse NDArray facade (parity: python/mxnet/ndarray/sparse.py).

Capability note (SURVEY.md §7 P6): the reference supports ``row_sparse`` and
``csr`` storage types end-to-end.  TPU/XLA has no sparse buffer type, so this
facade keeps the *API* (stype metadata, ``tostype``, ``row_sparse_array``,
``csr_matrix``) over dense device buffers with an explicit documented perf
caveat — numerics are identical, memory is dense.
"""
from __future__ import annotations

import numpy as np

from .ndarray import NDArray, array

__all__ = ["CSRNDArray", "RowSparseNDArray", "csr_matrix",
           "row_sparse_array", "zeros"]


class _SparseFacade(NDArray):
    __slots__ = ()
    _stype = "default"

    @property
    def stype(self):
        return self._stype

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, ctx=self._ctx)
        return _make(stype, self._data, self._ctx)


class CSRNDArray(_SparseFacade):
    __slots__ = ()
    _stype = "csr"

    @property
    def indices(self):
        a = self.asnumpy()
        return array(np.nonzero(a)[1].astype("int64"), ctx=self._ctx,
                     dtype="int64")

    @property
    def data(self):
        a = self.asnumpy()
        return array(a[a != 0], ctx=self._ctx)


class RowSparseNDArray(_SparseFacade):
    __slots__ = ()
    _stype = "row_sparse"

    @property
    def indices(self):
        a = self.asnumpy()
        nz = np.nonzero(np.any(a != 0, axis=tuple(range(1, a.ndim))))[0]
        return array(nz.astype("int64"), ctx=self._ctx, dtype="int64")


def _make(stype, data, ctx):
    cls = {"csr": CSRNDArray, "row_sparse": RowSparseNDArray}[stype]
    out = cls(data, ctx=ctx)
    return out


def csr_matrix(arg1, shape=None, ctx=None, dtype="float32"):
    if isinstance(arg1, (list, np.ndarray, NDArray)):
        base = array(arg1, ctx=ctx, dtype=dtype)
        return _make("csr", base._data, base._ctx)
    data, indices, indptr = arg1
    dense = np.zeros(shape, dtype=dtype)
    indptr = np.asarray(indptr, dtype="int64")
    indices = np.asarray(indices, dtype="int64")
    vals = np.asarray(data, dtype=dtype)
    rows = np.repeat(np.arange(shape[0]), np.diff(indptr))
    dense[rows, indices] = vals
    base = array(dense, ctx=ctx, dtype=dtype)
    return _make("csr", base._data, base._ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype="float32"):
    if isinstance(arg1, (list, np.ndarray, NDArray)) and shape is None:
        base = array(arg1, ctx=ctx, dtype=dtype)
        return _make("row_sparse", base._data, base._ctx)
    data, indices = arg1
    dense = np.zeros(shape, dtype=dtype)
    data = np.asarray(data, dtype=dtype)
    dense[np.asarray(indices, dtype="int64")] = data
    base = array(dense, ctx=ctx, dtype=dtype)
    return _make("row_sparse", base._data, base._ctx)


def zeros(stype, shape, ctx=None, dtype="float32"):
    from .ndarray import zeros as _dense_zeros
    base = _dense_zeros(shape, ctx=ctx, dtype=dtype)
    if stype == "default":
        return base
    return _make(stype, base._data, base._ctx)
