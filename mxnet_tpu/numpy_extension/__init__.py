"""``mx.npx`` — numpy-extension namespace (SURVEY.md §2.5: reference
``python/mxnet/numpy_extension``).

The reference's ``npx`` is where NN operators live under numpy
semantics: the np namespace stays pure-array-math, and everything
neural (activations, normed layers as functions, embedding/FC/conv,
sequence ops, special functions) plus the np-mode switches and engine
sync sits here.  The wrappers dispatch through the SAME op registry as
``mx.nd`` — one compiled implementation per op, two calling
conventions.
"""
from __future__ import annotations

import threading

from ..ndarray.ndarray import invoke
from ..ops.registry import get_op

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape",
           "relu", "sigmoid", "softmax", "log_softmax", "leaky_relu",
           "activation", "one_hot", "pick", "topk", "batch_dot",
           "reshape_like", "broadcast_like", "erf", "erfinv",
           "gamma", "gammaln", "smooth_l1", "sequence_mask",
           "embedding", "fully_connected", "convolution", "pooling",
           "batch_norm", "layer_norm", "dropout", "waitall"]

_state = threading.local()


def set_np(shape=True, array=True):
    """Enable numpy semantics flags (parity shim: our arrays already
    support zero-dim/zero-size shapes natively via XLA)."""
    _state.np_shape = bool(shape)
    _state.np_array = bool(array)


def reset_np():
    _state.np_shape = False
    _state.np_array = False


def is_np_array() -> bool:
    return getattr(_state, "np_array", False)


def is_np_shape() -> bool:
    return getattr(_state, "np_shape", False)


def _inv(op_name, inputs, **kw):
    return invoke(get_op(op_name), list(inputs), **kw)


# -- activations ------------------------------------------------------------

def relu(x):
    return _inv("relu", [x])


def sigmoid(x):
    return _inv("sigmoid", [x])


def softmax(x, axis=-1):
    return _inv("softmax", [x], axis=axis)


def log_softmax(x, axis=-1):
    return _inv("log_softmax", [x], axis=axis)


def leaky_relu(x, slope=0.25):
    return _inv("LeakyReLU", [x], act_type="leaky", slope=slope)


def activation(x, act_type="relu"):
    return _inv("Activation", [x], act_type=act_type)


# -- indexing / shape helpers ----------------------------------------------

def one_hot(x, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _inv("one_hot", [x], depth=depth, on_value=on_value,
                off_value=off_value, dtype=dtype)


def pick(data, index, axis=-1, mode="clip", keepdims=False):
    return _inv("pick", [data, index], axis=axis, mode=mode,
                keepdims=keepdims)


def topk(data, k=1, axis=-1, ret_typ="indices", is_ascend=False):
    return _inv("topk", [data], k=k, axis=axis, ret_typ=ret_typ,
                is_ascend=is_ascend)


def batch_dot(a, b, transpose_a=False, transpose_b=False):
    return _inv("batch_dot", [a, b], transpose_a=transpose_a,
                transpose_b=transpose_b)


def reshape_like(lhs, rhs):
    return lhs.reshape(rhs.shape)


def broadcast_like(lhs, rhs):
    return _inv("broadcast_like", [lhs, rhs])


def sequence_mask(data, valid_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    ins = [data] + ([valid_length] if valid_length is not None else [])
    return _inv("SequenceMask", ins,
                use_sequence_length=use_sequence_length, value=value,
                axis=axis)


# -- special functions ------------------------------------------------------

def erf(x):
    return _inv("erf", [x])


def erfinv(x):
    return _inv("erfinv", [x])


def gamma(x):
    return _inv("gamma", [x])


def gammaln(x):
    return _inv("gammaln", [x])


def smooth_l1(x, scalar=1.0):
    return _inv("smooth_l1", [x], scalar=scalar)


# -- NN layers as functions -------------------------------------------------

def embedding(data, weight, input_dim, output_dim, dtype="float32"):
    return _inv("Embedding", [data, weight], input_dim=input_dim,
                output_dim=output_dim, dtype=dtype)


def fully_connected(x, weight, bias=None, num_hidden=0,
                    no_bias=False, flatten=True):
    ins = [x, weight] + ([] if bias is None else [bias])
    return _inv("FullyConnected", ins, num_hidden=num_hidden,
                no_bias=bias is None or no_bias, flatten=flatten)


def convolution(data, weight, bias=None, kernel=(), stride=(),
                dilate=(), pad=(), num_filter=0, num_group=1,
                layout=None):
    ins = [data, weight] + ([] if bias is None else [bias])
    return _inv("Convolution", ins, kernel=kernel, stride=stride,
                dilate=dilate, pad=pad, num_filter=num_filter,
                num_group=num_group, no_bias=bias is None,
                layout=layout)


def pooling(data, kernel=(), pool_type="max", stride=(), pad=(),
            global_pool=False, pooling_convention="valid"):
    return _inv("Pooling", [data], kernel=kernel, pool_type=pool_type,
                stride=stride, pad=pad, global_pool=global_pool,
                pooling_convention=pooling_convention)


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-5,
               momentum=0.9, axis=1, use_global_stats=False):
    # delegate to the nd frontend: it owns the moving-stats aux update
    # and the training/inference switch (the raw op returns 3 outputs)
    from .. import ndarray as _nd
    return _nd.BatchNorm(x, gamma, beta, running_mean, running_var,
                         eps=eps, momentum=momentum, axis=axis,
                         fix_gamma=False,
                         use_global_stats=use_global_stats)


def layer_norm(x, gamma, beta, axis=-1, eps=1e-5):
    return _inv("LayerNorm", [x, gamma, beta], axis=axis, eps=eps)


def dropout(x, p=0.5, mode="training"):
    # delegate to the nd frontend: it threads the RNG key and the
    # training flag (the raw op requires an explicit key input)
    from .. import ndarray as _nd
    return _nd.Dropout(x, p=p, mode=mode)


def waitall():
    from ..ndarray import ndarray as nd_mod
    nd_mod.waitall()
