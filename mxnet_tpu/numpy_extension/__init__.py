"""``mx.npx`` — numpy-extension namespace (SURVEY.md §2.5: reference
``python/mxnet/numpy_extension`` / ``npx``): NN ops under numpy
semantics plus the np-mode switches."""
from __future__ import annotations

import threading

from ..ndarray.ndarray import NDArray, invoke
from ..ops.registry import get_op

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape",
           "relu", "sigmoid", "softmax", "log_softmax", "waitall",
           "one_hot"]

_state = threading.local()


def set_np(shape=True, array=True):
    """Enable numpy semantics flags (parity shim: our arrays already
    support zero-dim/zero-size shapes natively via XLA)."""
    _state.np_shape = bool(shape)
    _state.np_array = bool(array)


def reset_np():
    _state.np_shape = False
    _state.np_array = False


def is_np_array() -> bool:
    return getattr(_state, "np_array", False)


def is_np_shape() -> bool:
    return getattr(_state, "np_shape", False)


def _invoke1(op_name, x, **kw):
    return invoke(get_op(op_name), [x], **kw)


def relu(x):
    return _invoke1("relu", x)


def sigmoid(x):
    return _invoke1("sigmoid", x)


def softmax(x, axis=-1):
    return _invoke1("softmax", x, axis=axis)


def log_softmax(x, axis=-1):
    return _invoke1("log_softmax", x, axis=axis)


def one_hot(x, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _invoke1("one_hot", x, depth=depth, on_value=on_value,
                    off_value=off_value, dtype=dtype)


def waitall():
    from ..ndarray import ndarray as nd_mod
    nd_mod.waitall()
