"""Gluon Trainer: applies an optimizer over a set of Parameters.

Capability parity: reference ``python/mxnet/gluon/trainer.py`` (SURVEY.md
§2.5): kvstore wiring (``update_on_kvstore``), ``step(batch_size)`` =
allreduce_grads + update, ``rescale_grad`` folding, save/load optimizer
states, learning-rate surface.  On TPU a single process owns the mesh, so
"multi-device" gradient exchange is the kvstore's psum path (SURVEY.md
§2.3); with the default single-context setup allreduce is the identity.
"""
from __future__ import annotations

from typing import List, Optional

from ..base import MXNetError
from .. import optimizer as opt
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    """Applies ``optimizer`` over ``params`` each ``step()``.

    The update itself takes the FUSED path whenever the optimizer
    implements ``fused_update`` (SGD/Adam/LAMB): every parameter's
    update runs as ONE compiled multi-tensor dispatch with weight/state
    buffers donated, instead of one dispatch + Python hop per parameter.
    ``MXTPU_FUSED_UPDATE=0`` restores the per-param loop (escape hatch;
    the two paths are numerically identical — tier-1 tested).

    ``clip_global_norm``: optional max global gradient 2-norm, applied
    to the rescaled gradients across ALL parameters before the update —
    folded into the fused program (it needs every grad in one trace);
    the per-param fallback applies an equivalent pre-update clip.
    """

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None, clip_global_norm=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params: List[Parameter] = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        self._contexts = None
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        if clip_global_norm is not None:
            if not float(clip_global_norm) > 0:
                raise ValueError(
                    f"clip_global_norm must be positive, got "
                    f"{clip_global_norm}")
            self._optimizer.clip_global_norm = float(clip_global_norm)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_params = {
            "kvstore": kvstore,
            "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._states_to_init = False
        self._fused_decline_reported = False

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        # one updater per device context: each replica applies the same
        # reduced gradient, so the per-device optimizer states stay in sync
        # (parity: Trainer._updaters, one per context)
        contexts = self._check_contexts()
        self._contexts = contexts
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in contexts]

    def _check_contexts(self):
        # raises for fully-uninitialized params (parity: Trainer requires
        # initialize() before construction; deferred init returns its ctx
        # list, which is final)
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            if contexts is not None and contexts != ctx:
                raise ValueError(
                    "All Parameters must be initialized on the same "
                    f"set of contexts, but Parameter {param.name!r} is "
                    f"initialized on {ctx} while previous Parameters "
                    f"are initialized on {contexts}.")
            contexts = ctx
        return contexts if contexts is not None else [None]

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore = config["kvstore"]
        if kvstore is None:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            from .. import kvstore as kvs_mod
            if isinstance(kvstore, str):
                kvstore = kvs_mod.create(kvstore)
            self._kvstore = kvstore
            uok = config["update_on_kvstore"]
            self._update_on_kvstore = bool(uok) if uok is not None else \
                kvstore.is_distributed
            if self._compression_params:
                self._kvstore.set_gradient_compression(
                    self._compression_params)
            if self._update_on_kvstore:
                if getattr(self._optimizer, "clip_global_norm",
                           None) is not None:
                    raise ValueError(
                        "clip_global_norm requires update_on_kvstore="
                        "False: server-side updates see one gradient "
                        "at a time and cannot compute a global norm.")
                self._kvstore.set_optimizer(self._optimizer)
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    self._kvstore.init(str(i), param.data())
                    if kvstore.is_distributed:
                        # adopt the broadcast (rank 0) initial value so
                        # every worker trains the SAME model from step 1
                        # (the reference Trainer pulls right after init)
                        for ctx in param.list_ctx():
                            self._kvstore.pull(str(i),
                                               out=param.data(ctx))
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def compile_step(self, net, loss_fn):
        """A :class:`~mxnet_tpu.gluon.CompiledStep` running
        ``loss_fn(net(*data), label)`` + backward + THIS trainer's
        fused optimizer update as ONE donated compiled dispatch
        (escape hatch ``MXTPU_COMPILED_STEP=0``; transparent eager
        fallback otherwise — see docs/compiled_step.md)."""
        from .compiled_step import CompiledStep
        return CompiledStep(net, loss_fn, self)

    def warm_start(self, net, loss_fn, path):
        """:meth:`compile_step` + AOT precompile from a warm-start
        manifest (``CompiledStep.save_signature``): with a populated
        ``MXTPU_COMPILE_CACHE_DIR`` the whole fused train program is
        reloaded from disk BEFORE the first batch arrives — restart
        cost becomes O(disk read) instead of O(model compile).  Always
        returns the CompiledStep; ``.warm_started`` reports whether the
        precompile succeeded (failure is harmless — the first step
        compiles as usual).  See docs/compile_cache.md."""
        step = self.compile_step(net, loss_fn)
        step.warm_start(path)
        return step

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce grads, then apply optimizer scaled by 1/batch_size.

        ``rescale_grad`` (and lr/wd) ride as DYNAMIC scalars into the
        update ops, so stepping with a different ``batch_size`` never
        recompiles anything (regression-tested via
        ``engine.cache_info()``).
        """
        import time
        from .. import engine, telemetry
        t0 = time.perf_counter()
        d0 = engine.dispatch_count()
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if not self._allreduce_is_identity():
            self._allreduce_grads()
        self._update(ignore_stale_grad)
        if telemetry.enabled():
            if telemetry.step_owned():
                # a whole-step owner (CompiledStep eager fallback) is
                # on the stack and will do the step/throughput
                # accounting — record latency + dispatches only, so
                # nothing double-counts
                telemetry.histogram(
                    "mxtpu_trainer_step_seconds",
                    "Trainer.step (optimizer update) latency (s)"
                    ).observe(time.perf_counter() - t0)
                telemetry.gauge(
                    "mxtpu_trainer_step_dispatches",
                    "engine dispatches in the most recent Trainer.step"
                    ).set(engine.dispatch_count() - d0)
            else:
                # standalone record/backward/step loop: THIS is the
                # step owner — advance the global step counter so
                # retrace events get steady-state stamps (MXL306 would
                # otherwise read every retrace as warm-up, step 0)
                telemetry.record_step(
                    "trainer_step", time.perf_counter() - t0,
                    dispatches=engine.dispatch_count() - d0)

    def _allreduce_is_identity(self):
        """True when push+pull would only copy each gradient to the
        store and straight back: single replica, local (non-distributed)
        kvstore, no server-side update, no compression.  Skipping it
        folds the identity psum out of the hot path — the fused update
        is then the step's ONLY dispatch."""
        return (self._kvstore is not None
                and not self._kvstore.is_distributed
                and not self._update_on_kvstore
                and self._compression_params is None
                and len(self._contexts) == 1)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                self._kvstore.push(str(i), param.list_grad(), priority=-i)
                if not self._update_on_kvstore:
                    self._kvstore.pull(str(i), param.list_grad(),
                                       priority=-i, ignore_sparse=False)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._fused_eligible():
            if self._fused_update_all():
                return
            # fused path declined (optimizer lacks a fused program /
            # unsupported tensors): surface the degradation ONCE per
            # trainer — the per-param loop is ~P dispatches per step
            from .. import telemetry
            if not self._fused_decline_reported and telemetry.enabled():
                self._fused_decline_reported = True
                telemetry.counter(
                    "mxtpu_fallbacks_total",
                    "silent compiled->eager degradations").inc()
                telemetry.record_event(
                    "fallback", where="trainer_fused_update",
                    reason=f"optimizer {type(self._optimizer).__name__} "
                           "took the per-param update loop")
        if getattr(self._optimizer, "clip_global_norm", None) is not None \
                and not self._update_on_kvstore:
            self._clip_grads_global_norm()
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._kvstore is not None and self._update_on_kvstore:
                self._kvstore.pull(str(i), param.list_data(), priority=-i)
                continue
            for dev_id, (upd, arr, grad) in enumerate(
                    zip(self._updaters, param.list_data(),
                        param.list_grad())):
                # per-device update counts (parity: _set_current_context)
                # — each replica applies the same reduced grad once, so
                # Adam's t advances once per step, not once per device
                self._optimizer._set_current_context(dev_id)
                upd(i, grad, arr)

    # -- fused multi-tensor path ------------------------------------------
    def _fused_eligible(self):
        from .. import envs
        return (not self._update_on_kvstore
                and len(self._contexts) == 1
                and envs.get("MXTPU_FUSED_UPDATE"))

    def _fused_update_all(self):
        """Route the WHOLE parameter set through one fused dispatch.

        Returns False when the optimizer has no fused hook (or bails —
        e.g. row_sparse grads); the caller then runs the per-param loop,
        so behaviour degrades gracefully rather than erroring.
        """
        indices = [i for i, p in enumerate(self._params)
                   if p.grad_req != "null"]
        if not indices:
            return True
        weights = [self._params[i].data() for i in indices]
        grads = [self._params[i].list_grad()[0] for i in indices]
        self._optimizer._set_current_context(0)
        return self._updaters[0].call_fused(indices, grads, weights)

    def _clip_grads_global_norm(self):
        """Per-param-loop fallback for ``clip_global_norm``: scale the
        RAW grads so the rescaled grads' global norm is bounded —
        ``||rescale*g|| <= max_norm  <=>  ||g|| <= max_norm/rescale`` —
        which reproduces the fused program's clip exactly (rescale
        happens inside the update ops afterwards)."""
        from .utils import clip_global_norm as _cgn
        max_norm = float(self._optimizer.clip_global_norm)
        rescale = float(self._optimizer.rescale_grad)
        for dev_id in range(len(self._contexts)):
            grads = [p.list_grad()[dev_id] for p in self._params
                     if p.grad_req != "null"]
            if grads:
                _cgn(grads, max_norm / rescale, check_isfinite=False)

    # -- elastic protocol (docs/elasticity.md) ----------------------------
    def _elastic_export(self):
        """Checkpoint payload for ``elastic.CheckpointManager``: every
        parameter (incl. aux/BatchNorm stats), the updater's
        optimizer-state leaves, and the update counters."""
        from .compiled_step import _flatten_state
        opt = self._optimizer
        params = []
        for p in self._params:
            params.append((p.name, p.data()._data, "()"))
        states = []
        upd = self._updaters[0]
        for i, p in enumerate(self._params):
            st = upd.states.get(i)
            if st is None:
                continue
            leaves = []
            _flatten_state(st, leaves)
            for j, leaf in enumerate(leaves):
                states.append((i, j, leaf._data))
        step = max(opt._index_update_count.values(),
                   default=int(opt.num_update))
        return {
            "kind": "gluon", "step": int(step),
            "optimizer": type(opt).__name__,
            "update_counts": dict(opt._index_update_count),
            "num_update": int(opt.num_update),
            "mesh": None, "dp_axis": None, "persist_name": None,
            "params": params, "states": states, "residuals": [],
        }

    def _elastic_restore(self, payload):
        import jax
        import numpy as _np
        from .compiled_step import _flatten_state
        from ..elastic.manager import align_params
        aligned = align_params([p.name for p in self._params],
                               payload["params"])
        for p, (host, _spec) in zip(self._params, aligned):
            if tuple(host.shape) != tuple(p.data().shape):
                raise MXNetError(
                    f"checkpoint param {p.name!r} has shape "
                    f"{tuple(host.shape)}, trainer expects "
                    f"{tuple(p.data().shape)}")
            arr = _np.asarray(host)
            # every context replica, not just the primary — a stale
            # copy would diverge permanently on the next step
            for d in p.list_data():
                d._set_data(jax.device_put(arr, d.context.device))
        for i, j, host in payload["states"]:
            p = self._params[i]
            replicas = p.list_data()
            # one updater per context (step() pairs updater k with
            # replica k): every copy of the state must be restored or
            # the replicas diverge on the next step
            for k, upd in enumerate(self._updaters):
                upd._ensure_state(i, replicas[min(k, len(replicas) - 1)])
                leaves = []
                _flatten_state(upd.states[i], leaves)
                if j >= len(leaves):
                    raise MXNetError(
                        f"checkpoint optimizer-state leaf ({i},{j}) "
                        "out of range (optimizer class mismatch?)")
                leaves[j]._set_data(jax.device_put(
                    _np.asarray(host), leaves[j].context.device))
        opt = self._optimizer
        counts = {int(k): int(v)
                  for k, v in (payload.get("update_counts") or
                               {}).items()}
        # _index_update_count is an alias into the per-device dict of
        # whichever context stepped last — rewind EVERY device's copy
        # or multi-context Adam resumes with skewed bias-correction t
        for dev_counts in opt._all_index_update_counts.values():
            dev_counts.clear()
            dev_counts.update(counts)
        opt.num_update = int(payload.get("num_update",
                                         payload["step"]))

    def save_states(self, fname):
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            # the real states live in the kvstore's updater
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
            return
        with open(fname, "wb") as f:
            f.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
            self._optimizer.param_dict = {
                i: param for i, param in enumerate(self._params)}
            return
        with open(fname, "rb") as f:
            states = f.read()
        for updater in self._updaters:
            updater.set_states(states)
            updater.optimizer = self._updaters[0].optimizer
        self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {
            i: param for i, param in enumerate(self._params)}
