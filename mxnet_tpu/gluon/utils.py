"""Gluon utilities (parity: python/mxnet/gluon/utils.py).

``split_and_load`` is the reference's single-host data-parallel primitive;
here contexts may be multiple XLA host devices (tests) or TPU chips.
"""
from __future__ import annotations

import hashlib

import numpy as np

from ..base import MXNetError
from ..context import Context
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch axis into num_slice chunks."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}. Use a batch "
            f"size that's a multiple of {num_slice} or set even_split=False")
    step = size // num_slice
    if not even_split and size < num_slice:
        step = 1
        num_slice = size
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(nd.slice_axis(data, axis=batch_axis, begin=begin,
                                    end=end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split batch across contexts (parity: gluon.utils.split_and_load)."""
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays (in place) so that the global 2-norm <= max_norm.

    Same-context arrays take the fused ``clip_by_global_norm`` op: the
    norm reduction over EVERY array and all the scales run as ONE
    compiled dispatch instead of ~3 ops per array.
    """
    assert len(arrays) > 0
    ctx = arrays[0].context
    if all(a.context == ctx for a in arrays):
        outs = nd.clip_by_global_norm(*arrays, max_norm=float(max_norm))
        total_norm = outs[-1]
        for arr, scaled in zip(arrays, outs[:-1]):
            arr._set_data(scaled._data)
    else:
        # cross-context arrays cannot share one traced program
        def _norm(array):
            x = array.reshape((-1,))
            return nd.dot(x, x)
        total_norm = nd.add_n(*[_norm(a).as_in_context(ctx)
                                for a in arrays])
        total_norm = nd.sqrt(total_norm)
        scale = max_norm / (total_norm + 1e-8)
        scale = nd.minimum(scale, nd.ones((1,), ctx=ctx))
        for arr in arrays:
            arr *= scale.as_in_context(arr.context)
    if check_isfinite:
        val = float(total_norm.asscalar())
        if not np.isfinite(val):
            import warnings
            warnings.warn(
                UserWarning("nan or inf is detected. Clipping results will "
                            "be undefined."), stacklevel=2)
        return val
    return total_norm


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    raise MXNetError(
        "download() requires network access, which this environment does "
        "not provide (parity surface kept for API compatibility).")
