"""``gluon.contrib.estimator.Estimator`` — the reference's high-level
fit loop (``python/mxnet/gluon/contrib/estimator/estimator.py``).

One object owns net + loss + metrics + trainer and runs
epochs/batches, dispatching lifecycle events to handlers.  The TPU
build keeps the exact user contract (fit/evaluate, default handlers
created when none passed, train metrics named ``training <name>``,
validation metrics ``validation <name>``) while the inner step is the
standard record/backward/step triple — which hybridized nets execute
as whole-graph XLA.
"""
from __future__ import annotations

import copy
import logging

from .... import autograd
from ....context import Context, current_context
from ....metric import Accuracy, EvalMetric, Loss
from ...trainer import Trainer
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, MetricHandler,
                            StoppingHandler, TrainBegin, TrainEnd,
                            ValidationHandler)

__all__ = ["Estimator"]


class Estimator:
    def __init__(self, net, loss, metrics=None, initializer=None,
                 trainer=None, context=None):
        self.net = net
        self.loss = loss
        self.train_metrics = self._check_metrics(metrics)
        self.context = self._check_context(context)
        self._initialize(initializer)
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        if not self.train_metrics:
            self.train_metrics = [Accuracy()]
        self.train_loss_metric = Loss(
            f"training {getattr(loss, 'name', 'loss')}")
        # clone by deepcopy so constructor config (top_k, axis, ...)
        # survives — reconstructing via __class__() dropped it
        self.val_metrics = []
        for m in self.train_metrics:
            vm = copy.deepcopy(m)
            vm.name = f"validation {m.name}"
            vm.reset()
            self.val_metrics.append(vm)
        self.val_loss_metric = Loss(
            f"validation {getattr(loss, 'name', 'loss')}")
        for m in self.train_metrics:
            if not m.name.startswith("training"):
                m.name = f"training {m.name}"
        self.logger = logging.getLogger("mxnet_tpu.estimator")
        self.stop_training = False

    @staticmethod
    def _check_metrics(metrics):
        if metrics is None:
            return []
        metrics = metrics if isinstance(metrics, (list, tuple)) \
            else [metrics]
        for m in metrics:
            if not isinstance(m, EvalMetric):
                raise ValueError(
                    "metrics must be EvalMetric instances, got "
                    f"{type(m)}")
        return list(metrics)

    @staticmethod
    def _check_context(context):
        if context is None:
            return [current_context()]
        if isinstance(context, Context):
            return [context]
        return list(context)

    def _initialize(self, initializer):
        params = self.net.collect_params()
        uninit = [p for p in params.values() if p._data is None]
        if uninit:
            from .... import init as _init
            self.net.initialize(initializer or _init.Xavier(),
                                ctx=self.context[0])
        elif initializer is not None:
            # reference contract: an explicit initializer on an
            # already-initialized net is NOT applied — warn, don't
            # silently drop the request
            logging.getLogger("mxnet_tpu.estimator").warning(
                "Estimator: network already initialized; the passed "
                "initializer is ignored (call net.initialize("
                "force_reinit=True) first to re-initialize)")

    # -- evaluation --------------------------------------------------

    def evaluate(self, val_data, batch_axis=0):
        for m in [*self.val_metrics, self.val_loss_metric]:
            m.reset()
        for batch in val_data:
            data, label = self._unpack(batch)
            pred = self.net(data)
            loss = self.loss(pred, label)
            self.val_loss_metric.update(0, loss)
            for m in self.val_metrics:
                m.update(label, pred)
        return [m.get() for m in
                [*self.val_metrics, self.val_loss_metric]]

    def _unpack(self, batch):
        if hasattr(batch, "data"):          # DataBatch
            return batch.data[0], batch.label[0]
        data, label = batch[0], batch[1]
        ctx = self.context[0]
        if hasattr(data, "as_in_context"):
            data = data.as_in_context(ctx)
        if hasattr(label, "as_in_context"):
            label = label.as_in_context(ctx)
        return data, label

    # -- training ----------------------------------------------------

    def fit(self, train_data, val_data=None, epochs=None,
            event_handlers=None, batches=None, batch_axis=0):
        if epochs is None and batches is None:
            epochs = 1
        handlers = self._prepare_handlers(val_data, epochs, batches,
                                          event_handlers)
        categorized = {phase: [h for h in handlers
                               if isinstance(h, base)]
                       for phase, base in (
                           ("train_begin", TrainBegin),
                           ("epoch_begin", EpochBegin),
                           ("batch_begin", BatchBegin),
                           ("batch_end", BatchEnd),
                           ("epoch_end", EpochEnd),
                           ("train_end", TrainEnd))}

        for h in categorized["train_begin"]:
            h.train_begin(self)
        self.stop_training = False
        while not self.stop_training:
            for h in categorized["epoch_begin"]:
                h.epoch_begin(self)
            self.train_loss_metric.reset()
            for batch in train_data:
                for h in categorized["batch_begin"]:
                    h.batch_begin(self, batch=batch)
                data, label = self._unpack(batch)
                with autograd.record():
                    pred = self.net(data)
                    loss = self.loss(pred, label)
                loss.backward()
                bs = data.shape[batch_axis]
                self.trainer.step(bs)
                self.train_loss_metric.update(0, loss)
                for h in categorized["batch_end"]:
                    h.batch_end(self, batch=batch, pred=pred,
                                label=label, loss=loss)
                if self._should_stop(handlers):
                    break
            for h in categorized["epoch_end"]:
                h.epoch_end(self)
            if self._should_stop(handlers):
                break
        for h in categorized["train_end"]:
            h.train_end(self)

    def _should_stop(self, handlers):
        if any(getattr(h, "stop_training", False) for h in handlers):
            self.stop_training = True
        return self.stop_training

    def _prepare_handlers(self, val_data, epochs, batches,
                          event_handlers):
        handlers = list(event_handlers or [])
        has = lambda cls: any(isinstance(h, cls) for h in handlers)
        if val_data is not None and not has(ValidationHandler):
            # FIRST in the list: epoch_end hooks run in handler order,
            # and checkpoint/early-stop handlers monitoring a
            # validation metric must see THIS epoch's value, not the
            # previous one (the reference gives validation top
            # priority for the same reason)
            handlers.insert(0, ValidationHandler(val_data,
                                                 self.evaluate))
        if not has(StoppingHandler):
            handlers.append(StoppingHandler(max_epoch=epochs,
                                            max_batch=batches))
        if not has(MetricHandler):
            handlers.append(MetricHandler(self.train_metrics))
        if not has(LoggingHandler):
            handlers.append(LoggingHandler(
                metrics=[*self.train_metrics, self.train_loss_metric]))
        return handlers
