"""``mx.gluon.contrib.estimator`` (reference:
``python/mxnet/gluon/contrib/estimator/``): high-level fit loop +
lifecycle event handlers."""
from .estimator import Estimator
from .event_handler import (BatchBegin, BatchEnd, CheckpointHandler,
                            EarlyStoppingHandler, EpochBegin, EpochEnd,
                            LoggingHandler, MetricHandler,
                            StoppingHandler, TrainBegin, TrainEnd,
                            ValidationHandler)

__all__ = [
    "Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
    "BatchBegin", "BatchEnd", "StoppingHandler", "MetricHandler",
    "ValidationHandler", "LoggingHandler", "CheckpointHandler",
    "EarlyStoppingHandler",
]
