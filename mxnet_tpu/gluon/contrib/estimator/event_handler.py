"""Estimator event handlers (reference:
``python/mxnet/gluon/contrib/estimator/event_handler.py``).

Handlers subscribe to the fit loop's lifecycle by mixing in any of the
six marker bases; the Estimator calls every subscribed hook in handler
order.  Built-ins cover the reference's roster: stopping on
batch/epoch quota, metric bookkeeping, validation, logging,
checkpointing, and early stopping.
"""
from __future__ import annotations

import logging
import os
import time

__all__ = [
    "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
    "BatchEnd", "StoppingHandler", "MetricHandler",
    "ValidationHandler", "LoggingHandler", "CheckpointHandler",
    "EarlyStoppingHandler",
]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop after ``max_epoch`` epochs or ``max_batch`` total batches
    (whichever comes first), like the reference's quota handler."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Resets training metrics at epoch start and feeds them each
    batch (reference behavior: metrics passed to Estimator update
    automatically)."""

    def __init__(self, metrics):
        self.metrics = metrics

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        from ....metric import Loss as _LossMetric
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.metrics:
            if isinstance(m, _LossMetric) and loss is not None:
                m.update(0, loss)
            elif pred is not None and label is not None:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Runs ``eval_fn`` every ``epoch_period`` epochs (or
    ``batch_period`` batches) and stores results on the estimator."""

    def __init__(self, val_data, eval_fn, epoch_period=1,
                 batch_period=None):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and \
                self.current_batch % self.batch_period == 0:
            self.eval_fn(self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                     BatchEnd):
    """Per-epoch (and optionally per-N-batch) metric logging with
    throughput, like the reference's LoggingHandler + Speedometer."""

    def __init__(self, log_interval="epoch", metrics=None):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.logger = logging.getLogger("mxnet_tpu.estimator")
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        secs = time.time() - self.train_start
        self.logger.info("Training finished in %.1fs (%d epochs)",
                         secs, self.current_epoch)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()
        self.batch_index = 0
        self.processed_samples = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.batch_index += 1
        batch = kwargs.get("batch")
        if batch is not None:
            try:
                self.processed_samples += batch[0].shape[0]
            except Exception:
                pass
        if isinstance(self.log_interval, int) and \
                self.batch_index % self.log_interval == 0:
            msgs = [f"{n}={v:.4f}" if isinstance(v, float)
                    else f"{n}={v}"
                    for n, v in (m.get() for m in self.metrics)]
            self.logger.info("[epoch %d batch %d] %s",
                             self.current_epoch, self.batch_index,
                             " ".join(msgs))

    def epoch_end(self, estimator, *args, **kwargs):
        secs = time.time() - self.epoch_start
        sps = self.processed_samples / secs if secs > 0 else 0.0
        msgs = [f"{n}={v:.4f}" if isinstance(v, float) else f"{n}={v}"
                for n, v in (m.get() for m in self.metrics)]
        self.logger.info("[epoch %d] time %.1fs %.0f samples/s %s",
                         self.current_epoch, secs, sps, " ".join(msgs))
        self.current_epoch += 1


class CheckpointHandler(TrainBegin, EpochEnd):
    """Saves ``{prefix}-epochN.params`` each epoch; with
    ``monitor``+``save_best`` also keeps ``{prefix}-best.params``
    (reference CheckpointHandler contract)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 mode="min", save_best=False, epoch_period=1):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.mode = mode
        self.current_epoch = 0
        self.best = float("inf") if mode == "min" else -float("inf")

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)
        self.current_epoch = 0
        # a second fit() is a fresh run: a stale best from the previous
        # run must not suppress this run's best checkpoint (ADVICE r3)
        self.best = float("inf") if self.mode == "min" \
            else -float("inf")

    def _improved(self, value):
        return value < self.best if self.mode == "min" \
            else value > self.best

    def epoch_end(self, estimator, *args, **kwargs):
        if self.current_epoch % self.epoch_period == 0:
            path = os.path.join(
                self.model_dir,
                f"{self.model_prefix}-epoch{self.current_epoch}"
                ".params")
            estimator.net.save_parameters(path)
        if self.save_best and self.monitor is not None:
            _, value = self.monitor.get()
            if isinstance(value, (int, float)) and \
                    self._improved(value):
                self.best = value
                estimator.net.save_parameters(os.path.join(
                    self.model_dir,
                    f"{self.model_prefix}-best.params"))
        self.current_epoch += 1


class EarlyStoppingHandler(TrainBegin, EpochEnd):
    """Stops training when ``monitor`` hasn't improved by
    ``min_delta`` for ``patience`` epochs (reference contract)."""

    def __init__(self, monitor, mode="min", patience=3, min_delta=0.0,
                 baseline=None):
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self.baseline = baseline
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.stop_training = False
        if self.baseline is not None:
            self.best = self.baseline
        else:
            self.best = float("inf") if self.mode == "min" \
                else -float("inf")

    def _improved(self, value):
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def epoch_end(self, estimator, *args, **kwargs):
        _, value = self.monitor.get()
        if not isinstance(value, (int, float)):
            return
        if self._improved(value):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
