"""``gluon.contrib.cnn`` — deformable convolution layer (reference:
``python/mxnet/gluon/contrib/cnn/conv_layers.py``)."""
from __future__ import annotations

from ..block import HybridBlock
from ..nn.conv_layers import Conv2D

__all__ = ["DeformableConvolution"]


class DeformableConvolution(HybridBlock):
    """2-D deformable convolution (v1): an internal regular conv
    predicts per-tap sampling offsets, and the main kernel samples the
    input bilinearly at base+offset positions
    (``_contrib_DeformableConvolution``; reference
    ``src/operator/contrib/deformable_convolution.cc`` + the gluon
    contrib layer).  The offset branch is zero-initialized so the
    layer starts as a plain convolution.
    """

    def __init__(self, channels, kernel_size=(3, 3), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, in_channels=0, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 offset_use_bias=True, activation=None, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        ks = (kernel_size,) * 2 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        strides = (strides,) * 2 if isinstance(strides, int) \
            else tuple(strides)
        padding = (padding,) * 2 if isinstance(padding, int) \
            else tuple(padding)
        dilation = (dilation,) * 2 if isinstance(dilation, int) \
            else tuple(dilation)
        self._channels = channels
        self._kwargs = {
            "kernel": ks, "stride": strides, "pad": padding,
            "dilate": dilation, "num_filter": channels,
            "num_group": groups,
            "num_deformable_group": num_deformable_group,
            "no_bias": not use_bias}
        with self.name_scope():
            # offsets start at zero → identity sampling grid
            self.offset_conv = Conv2D(
                2 * num_deformable_group * ks[0] * ks[1], ks,
                strides=strides, padding=padding, dilation=dilation,
                in_channels=in_channels, use_bias=offset_use_bias,
                weight_initializer="zeros",
                bias_initializer="zeros", prefix="offset_")
            self.weight = self.params.get(
                "weight",
                shape=(channels,
                       in_channels // groups if in_channels else 0)
                + ks,
                init=weight_initializer, allow_deferred_init=True)
            self.bias = self.params.get(
                "bias", shape=(channels,), init=bias_initializer,
                allow_deferred_init=True) if use_bias else None
            if activation is not None:
                from ..nn.activations import Activation
                self.act = Activation(activation)
            else:
                self.act = None

    def infer_shape(self, x):
        groups = self._kwargs["num_group"]
        self.weight.shape = (self._channels, x.shape[1] // groups) + \
            self._kwargs["kernel"]

    def hybrid_forward(self, F, x, weight, bias=None):
        offset = self.offset_conv(x)
        op = getattr(F, "_contrib_DeformableConvolution")
        if bias is None:
            out = op(x, offset, weight, **self._kwargs)
        else:
            out = op(x, offset, weight, bias, **self._kwargs)
        return self.act(out) if self.act is not None else out
