"""Transformer building blocks (capability target: GluonNLP's
``gluonnlp.model.transformer``/BERT blocks — SURVEY.md §2.6 "External
zoos" and §5 "Long-context").

Built on the fused ``dot_product_attention`` op (Pallas flash path on
TPU): one op per attention instead of the reference's interleaved-matmul
chains.
"""
from __future__ import annotations

import math

import numpy as np

from ...base import MXNetError
from ..block import HybridBlock
from .. import nn

__all__ = ["MultiHeadAttention", "PositionwiseFFN",
           "TransformerEncoderCell", "TransformerEncoder", "MoEFFN",
           "SyncBatchNorm"]


class MultiHeadAttention(HybridBlock):
    """Multi-head self/cross attention (units == num_heads * head_dim)."""

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by num_heads "
                             f"{num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._dropout = dropout
        with self.name_scope():
            self.query_proj = nn.Dense(units, flatten=False,
                                       use_bias=use_bias, prefix="query_")
            self.key_proj = nn.Dense(units, flatten=False,
                                     use_bias=use_bias, prefix="key_")
            self.value_proj = nn.Dense(units, flatten=False,
                                       use_bias=use_bias, prefix="value_")
            self.out_proj = nn.Dense(units, flatten=False,
                                     use_bias=use_bias, prefix="out_")
            self.drop = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, query, key=None, value=None, mask=None):
        if key is None:
            key = query
        if value is None:
            value = key
        b, s_q = query.shape[0], query.shape[1]
        s_k = key.shape[1]
        h = self._num_heads
        d = self._units // h
        q = self.query_proj(query).reshape((b, s_q, h, d))
        k = self.key_proj(key).reshape((b, s_k, h, d))
        v = self.value_proj(value).reshape((b, s_k, h, d))
        if mask is not None:
            out = F.dot_product_attention(q, k, v, mask, use_mask=True)
        else:
            out = F.dot_product_attention(q, k, v)
        out = out.reshape((b, s_q, self._units))
        out = self.out_proj(out)
        if self.drop is not None:
            out = self.drop(out)
        return out


class PositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, flatten=False,
                                  prefix="ffn1_")
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn2_")
            self.drop = nn.Dropout(dropout) if dropout else None
        self._activation = activation

    def hybrid_forward(self, F, x):
        h = self.ffn_1(x)
        if self._activation == "gelu":
            h = F.LeakyReLU(h, act_type="gelu")
        else:
            h = F.Activation(h, act_type=self._activation)
        h = self.ffn_2(h)
        if self.drop is not None:
            h = self.drop(h)
        return h


# trace-time count of rematerialized encoder stacks (tests assert the
# checkpoint branch actually fired, not merely that numerics matched)
_REMAT_APPLICATIONS = 0

# trace-time count of scan-over-layers encoder stacks (same contract)
_SCAN_APPLICATIONS = 0


class TransformerEncoderCell(HybridBlock):
    """Pre/post-LN encoder layer (BERT uses post-LN, the default)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 activation="gelu", pre_norm=False, **kwargs):
        super().__init__(**kwargs)
        self._pre_norm = pre_norm
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads,
                                                dropout=dropout)
            self.ffn = PositionwiseFFN(units, hidden_size,
                                       dropout=dropout,
                                       activation=activation)
            self.layer_norm_att = nn.LayerNorm(in_channels=units)
            self.layer_norm_ffn = nn.LayerNorm(in_channels=units)
            self.drop = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, mask=None):
        # Block.__call__ is positional: (query, key, value, mask)
        if self._pre_norm:
            att = self.attention(self.layer_norm_att(x), None, None, mask)
            x = x + att
            out = self.ffn(self.layer_norm_ffn(x))
            return x + out
        att = self.attention(x, None, None, mask)
        if self.drop is not None:
            att = self.drop(att)
        x = self.layer_norm_att(x + att)
        out = self.ffn(x)
        return self.layer_norm_ffn(x + out)


class TransformerEncoder(HybridBlock):
    """Stack of encoder cells.

    ``remat=True`` wraps each layer in ``jax.checkpoint`` when running
    inside a jitted trace (the fused trainer, hybridized forward):
    activations are recomputed during backward instead of stored, so
    batch x seq configurations that would overflow HBM fit — the
    standard FLOPs-for-memory trade on TPU.  Numerically identical to
    the uncheckpointed stack (same program, different schedule).

    ``scan_layers=True`` runs the stack as ONE ``lax.scan`` over
    stacked per-layer weights instead of unrolling N layers into the
    program.  Same math, same parameters (stacked at trace time, so
    gradients flow to each layer's own tensors) — but the compiled
    program contains ONE layer body, cutting XLA compile time ~N-fold.
    The TPU-first shape for deep transformers: the reference unrolls
    because graph-per-layer is how imperative frameworks work; under a
    tracing compiler the loop belongs in the IR (``lax.scan``), not the
    Python. Composes with ``remat`` (the scan body is checkpointed).
    Dropout draws a distinct folded key per layer, matching the
    unrolled stack's per-layer independence."""

    def __init__(self, units, hidden_size, num_layers, num_heads,
                 dropout=0.0, activation="gelu", pre_norm=False,
                 remat=False, scan_layers=False, **kwargs):
        super().__init__(**kwargs)
        self._remat = remat
        self._scan_layers = scan_layers
        with self.name_scope():
            self.layers = []
            for i in range(num_layers):
                cell = TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout=dropout,
                    activation=activation, pre_norm=pre_norm,
                    prefix=f"layer{i}_")
                self.register_child(cell, f"layer{i}")
                self.layers.append(cell)

    def _cell_param_refs(self, cell):
        """(suffix, NDArray) pairs in a deterministic order shared by
        every cell — suffixes are the param names with the per-layer
        prefix stripped."""
        pfx = cell.prefix
        items = []
        for name, p in cell.collect_params().items():
            suffix = name[len(pfx):] if name.startswith(pfx) else name
            items.append((suffix, p.data()))
        items.sort(key=lambda kv: kv[0])
        return items

    def _scan_forward(self, x, mask):
        import jax
        import jax.numpy as jnp
        from ... import random as _rnd
        from ...ndarray.ndarray import NDArray

        global _SCAN_APPLICATIONS
        _SCAN_APPLICATIONS += 1
        ctx = x.context
        cell0 = self.layers[0]
        ref_items = self._cell_param_refs(cell0)
        refs = [nd for _, nd in ref_items]
        order = [s for s, _ in ref_items]

        layer_bufs = []
        for cell in self.layers:
            items = dict(self._cell_param_refs(cell))
            if sorted(items) != sorted(order):
                raise MXNetError(
                    "scan_layers=True needs structurally identical "
                    f"cells; {cell.prefix} params differ from "
                    f"{cell0.prefix}")
            layer_bufs.append([items[s]._buf for s in order])
        stacked = tuple(
            jnp.stack([bufs[i] for bufs in layer_bufs])
            for i in range(len(order)))

        # one ambient key, folded per layer INSIDE the scan so each
        # layer's dropout masks are independent (as in the unrolled
        # stack); nested fold_in inside the body separates multiple
        # dropout sites within a layer
        base = _rnd._next_key_nd(ctx)._data
        layer_keys = jnp.stack([
            jax.random.key_data(jax.random.fold_in(
                jax.random.wrap_key_data(base), i))
            for i in range(len(self.layers))])

        def body(carry, xs):
            sliced, kraw = xs[:-1], xs[-1]
            counter = [0]

            def provider(_ctx):
                k = jax.random.fold_in(
                    jax.random.wrap_key_data(kraw), counter[0])
                counter[0] += 1
                return NDArray(jax.random.key_data(k), ctx=ctx)

            saved = [(r._buf, r._version) for r in refs]
            _rnd._push_key_provider(provider)
            try:
                for r, s in zip(refs, sliced):
                    r._buf = s
                out = cell0(NDArray(carry, ctx=ctx), mask)
            finally:
                _rnd._pop_key_provider()
                for r, (b, v) in zip(refs, saved):
                    r._buf = b
                    r._version = v
            return out._data, None

        if self._remat:
            # the remat-fired counter must also reflect this path — a
            # checkpointed scan body IS the remat contract applying
            global _REMAT_APPLICATIONS
            _REMAT_APPLICATIONS += 1
            body = jax.checkpoint(body)
        out, _ = jax.lax.scan(body, x._data, stacked + (layer_keys,))
        return NDArray(out, ctx=ctx)

    def hybrid_forward(self, F, x, mask=None):
        from ..block import _is_tracing
        if self._scan_layers and len(self.layers) > 1 and _is_tracing():
            return self._scan_forward(x, mask)
        if self._remat and _is_tracing():
            import jax
            from ...ndarray.ndarray import NDArray
            global _REMAT_APPLICATIONS
            _REMAT_APPLICATIONS += 1
            ctx = x.context
            for layer in self.layers:
                def body(xv, mv, _layer=layer):
                    m = NDArray(mv, ctx=ctx) if mv is not None else None
                    return _layer(NDArray(xv, ctx=ctx), m)._data

                if mask is None:
                    x = NDArray(jax.checkpoint(
                        lambda xv, _l=layer: body(xv, None, _l))(
                            x._data), ctx=ctx)
                else:
                    x = NDArray(jax.checkpoint(body)(
                        x._data, mask._data), ctx=ctx)
            return x
        for layer in self.layers:
            x = layer(x, mask)
        return x


class MoEFFN(HybridBlock):
    """Mixture-of-experts feed-forward layer (beyond-reference; see
    ops/moe.py).  Input (B, S, d) or (T, d); top-k routing with static
    capacity; expert weights live as (E, ...) tensors so an ``ep`` mesh
    axis can shard them (``parallel.moe_param_rule``).

    ``forward`` returns ``(out, aux_loss)``; add ``aux_weight *
    aux_loss`` to the training loss for load balancing.
    """

    def __init__(self, units, hidden_size, num_experts, k=1,
                 capacity_factor=1.25, activation="relu", prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._e = num_experts
        self._kwargs = {"num_experts": num_experts, "k": k,
                        "capacity_factor": capacity_factor,
                        "activation": activation}
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(units, num_experts))
            self.expert_w1 = self.params.get(
                "expert_w1", shape=(num_experts, units, hidden_size))
            self.expert_b1 = self.params.get(
                "expert_b1", shape=(num_experts, hidden_size),
                init="zeros")
            self.expert_w2 = self.params.get(
                "expert_w2", shape=(num_experts, hidden_size, units))
            self.expert_b2 = self.params.get(
                "expert_b2", shape=(num_experts, units), init="zeros")

    def hybrid_forward(self, F, x, gate_weight, expert_w1, expert_b1,
                       expert_w2, expert_b2):
        shape = x.shape
        flat = x.reshape((-1, self._units)) if len(shape) > 2 else x
        out, aux = F._contrib_MoEFFN(flat, gate_weight, expert_w1,
                                     expert_b1, expert_w2, expert_b2,
                                     **self._kwargs)
        if len(shape) > 2:
            out = out.reshape(shape)
        return out, aux


class SyncBatchNorm(nn.BatchNorm):
    """Cross-device synchronized BatchNorm (parity: reference
    ``gluon.contrib.nn.SyncBatchNorm``).

    The reference implements this with a dedicated cross-GPU allreduce
    of batch statistics (``sync_batch_norm.cc``).  Under this
    framework's SPMD execution model it needs NO extra communication
    code: inside a mesh-jitted step (``DataParallelTrainer``) the batch
    dim is sharded but the BatchNorm reduction is over the GLOBAL batch
    — XLA inserts the cross-device reduction automatically, which IS
    sync-BN semantics (verified bit-exact in tests/test_parallel.py).
    The class exists so reference code importing SyncBatchNorm ports
    unchanged; ``num_devices``/``ndev`` is accepted and ignored.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        # positional layout matches the reference exactly so ported
        # SyncBatchNorm(64, 4, 0.99) keeps its momentum
        kwargs.pop("ndev", None)
        kwargs.pop("key", None)
        super().__init__(in_channels=in_channels, momentum=momentum,
                         epsilon=epsilon, **kwargs)
