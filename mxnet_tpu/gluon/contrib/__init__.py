"""``mx.gluon.contrib``: transformer blocks and other staging-ground
layers (SURVEY.md §2.2 contrib)."""
from . import nn

__all__ = ["nn"]
