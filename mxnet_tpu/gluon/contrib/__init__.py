"""``mx.gluon.contrib``: transformer blocks, the Estimator fit loop,
and other staging-ground layers (SURVEY.md §2.2 contrib)."""
from . import estimator, nn

__all__ = ["nn", "estimator"]
