"""``mx.gluon.contrib``: transformer blocks, the Estimator fit loop,
deformable convolution, and other staging-ground layers (SURVEY.md
§2.2 contrib)."""
from . import cnn, estimator, nn

__all__ = ["nn", "estimator", "cnn"]
