"""Activation layers (parity: reference gluon/nn/activations.py)."""
from __future__ import annotations

from ..block import HybridBlock


class Activation(HybridBlock):
    """Named activation: relu/sigmoid/tanh/softrelu/softsign."""

    def __init__(self, activation, prefix=None, params=None):
        self._act_type = activation
        super().__init__(prefix=prefix, params=params)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, prefix=None, params=None):
        assert alpha >= 0, "Slope coefficient for LeakyReLU must be >= 0."
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return f"LeakyReLU({self._alpha})"


class PReLU(HybridBlock):
    """Leaky ReLU with learned slope (per-channel)."""

    def __init__(self, alpha_initializer=None, in_channels=1, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        from ... import initializer
        if alpha_initializer is None:
            alpha_initializer = initializer.Constant(0.25)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(in_channels,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, approximation="erf", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._approx = approximation

    def hybrid_forward(self, F, x):
        if self._approx == "tanh":
            return F.gelu_tanh(x)
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    """x * sigmoid(beta * x)."""

    def __init__(self, beta=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._beta = beta

    def hybrid_forward(self, F, x):
        if self._beta == 1.0:
            return F.silu(x)
        return x * F.sigmoid(self._beta * x)


class SiLU(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return F.silu(x)
