"""Convolution and pooling layers.

Capability parity: reference ``python/mxnet/gluon/nn/conv_layers.py``
(Conv1D/2D/3D, transposed variants, Max/Avg/Global pooling) — SURVEY.md
§2.5.  Layout is MXNet's NCW/NCHW/NCDHW API-side; XLA relayouts for the MXU
internally.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ..block import HybridBlock


def _to_tuple(val, n):
    if isinstance(val, (int, np.integer)):
        return (int(val),) * n
    assert len(val) == n
    return tuple(int(v) for v in val)


class _Conv(HybridBlock):
    """Shared conv machinery (parity: _Conv base in the reference)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", op_name="Convolution",
                 adj=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._channels = channels
        self._in_channels = in_channels
        ndim = len(kernel_size)
        self._ndim = ndim
        self._op_name = op_name
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        with self.name_scope():
            if op_name == "Convolution":
                wshape = (channels, in_channels // groups
                          if in_channels else 0) + kernel_size
            else:  # Deconvolution: (in_channels, channels, *kernel)
                wshape = (in_channels, channels // groups) + kernel_size
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                from .activations import Activation
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x):
        in_c = x.shape[1]
        groups = self._kwargs["num_group"]
        if self._op_name == "Convolution":
            self.weight.shape = (self._channels, in_c // groups) + \
                self._kwargs["kernel"]
        else:
            self.weight.shape = (in_c, self._channels // groups) + \
                self._kwargs["kernel"]
        self._in_channels = in_c

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            out = op(x, weight, **self._kwargs)
        else:
            out = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return (f"{self.__class__.__name__}({self._in_channels} -> "
                f"{self._channels}, kernel_size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, prefix=None,
                 params=None):
        assert layout == "NCW", "Only NCW layout is supported"
        super().__init__(channels, _to_tuple(kernel_size, 1),
                         _to_tuple(strides, 1), _to_tuple(padding, 1),
                         _to_tuple(dilation, 1), groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, prefix=prefix, params=params)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1, layout="NCHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, prefix=None,
                 params=None):
        assert layout in ("NCHW",), "Only NCHW layout is supported"
        super().__init__(channels, _to_tuple(kernel_size, 2),
                         _to_tuple(strides, 2), _to_tuple(padding, 2),
                         _to_tuple(dilation, 2), groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, prefix=prefix, params=params)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, prefix=None, params=None):
        assert layout == "NCDHW", "Only NCDHW layout is supported"
        super().__init__(channels, _to_tuple(kernel_size, 3),
                         _to_tuple(strides, 3), _to_tuple(padding, 3),
                         _to_tuple(dilation, 3), groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, prefix=prefix, params=params)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, prefix=None,
                 params=None):
        super().__init__(channels, _to_tuple(kernel_size, 1),
                         _to_tuple(strides, 1), _to_tuple(padding, 1),
                         _to_tuple(dilation, 1), groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_to_tuple(output_padding, 1), prefix=prefix,
                         params=params)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), output_padding=(0, 0), dilation=(1, 1),
                 groups=1, layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, prefix=None, params=None):
        super().__init__(channels, _to_tuple(kernel_size, 2),
                         _to_tuple(strides, 2), _to_tuple(padding, 2),
                         _to_tuple(dilation, 2), groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_to_tuple(output_padding, 2), prefix=prefix,
                         params=params)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, prefix=None,
                 params=None):
        super().__init__(channels, _to_tuple(kernel_size, 3),
                         _to_tuple(strides, 3), _to_tuple(padding, 3),
                         _to_tuple(dilation, 3), groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_to_tuple(output_padding, 3), prefix=prefix,
                         params=params)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=None, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid"}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return (f"{self.__class__.__name__}(size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']}, "
                f"padding={self._kwargs['pad']})")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, prefix=None, params=None):
        super().__init__(_to_tuple(pool_size, 1),
                         None if strides is None else _to_tuple(strides, 1),
                         _to_tuple(padding, 1), ceil_mode, False, "max",
                         layout, prefix=prefix, params=params)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, prefix=None, params=None):
        super().__init__(_to_tuple(pool_size, 2),
                         None if strides is None else _to_tuple(strides, 2),
                         _to_tuple(padding, 2), ceil_mode, False, "max",
                         layout, prefix=prefix, params=params)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, prefix=None, params=None):
        super().__init__(_to_tuple(pool_size, 3),
                         None if strides is None else _to_tuple(strides, 3),
                         _to_tuple(padding, 3), ceil_mode, False, "max",
                         layout, prefix=prefix, params=params)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, prefix=None,
                 params=None):
        super().__init__(_to_tuple(pool_size, 1),
                         None if strides is None else _to_tuple(strides, 1),
                         _to_tuple(padding, 1), ceil_mode, False, "avg",
                         layout, count_include_pad, prefix=prefix,
                         params=params)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 prefix=None, params=None):
        super().__init__(_to_tuple(pool_size, 2),
                         None if strides is None else _to_tuple(strides, 2),
                         _to_tuple(padding, 2), ceil_mode, False, "avg",
                         layout, count_include_pad, prefix=prefix,
                         params=params)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 prefix=None, params=None):
        super().__init__(_to_tuple(pool_size, 3),
                         None if strides is None else _to_tuple(strides, 3),
                         _to_tuple(padding, 3), ceil_mode, False, "avg",
                         layout, count_include_pad, prefix=prefix,
                         params=params)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", prefix=None, params=None):
        super().__init__((1,), None, (0,), False, True, "max", layout,
                         prefix=prefix, params=params)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", prefix=None, params=None):
        super().__init__((1, 1), None, (0, 0), False, True, "max", layout,
                         prefix=prefix, params=params)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", prefix=None, params=None):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "max",
                         layout, prefix=prefix, params=params)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", prefix=None, params=None):
        super().__init__((1,), None, (0,), False, True, "avg", layout,
                         prefix=prefix, params=params)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", prefix=None, params=None):
        super().__init__((1, 1), None, (0, 0), False, True, "avg", layout,
                         prefix=prefix, params=params)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", prefix=None, params=None):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "avg",
                         layout, prefix=prefix, params=params)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(padding, (int, np.integer)):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        assert len(padding) == 8
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
