"""Basic Gluon layers.

Capability parity: reference ``python/mxnet/gluon/nn/basic_layers.py``
(Dense, Dropout, BatchNorm, LayerNorm, InstanceNorm, Embedding, Flatten,
Sequential/HybridSequential, Lambda/HybridLambda) — SURVEY.md §2.5.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ..block import Block, HybridBlock
from ..parameter import Parameter


class Sequential(Block):
    """Stack of Blocks executed sequentially (imperative-only)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers[key])
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        """Sequential containers only pass hybridize down to children."""
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                "All children of this Sequential layer are HybridBlocks. "
                "Consider using HybridSequential for the best performance.",
                stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks; hybridizable as one graph."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers[key])
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer: ``out = act(dot(x, W.T) + b)``.

    Parity: reference ``nn.Dense`` incl. deferred ``in_units`` (weight shape
    ``(units, 0)`` completed at first forward) and ``flatten``.
    """

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._flatten = flatten
        self._units = units
        self._in_units = in_units
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                from .activations import Activation
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x):
        in_units = int(np.prod(x.shape[1:])) if self._flatten \
            else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, *([bias] if bias is not None
                                            else []),
                               num_hidden=self._units,
                               no_bias=bias is None,
                               flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape[1] else None} -> {shape[0]}, "
                f"{'linear' if self.act is None else self.act._act_type})")


class Dropout(HybridBlock):
    """Dropout; active only in train mode (autograd.train_mode)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with moving-average aux state.

    Parity: reference ``nn.BatchNorm`` — ``use_global_stats``, ``scale``
    (fix_gamma inverse), ``center``, channel ``axis``, deferred
    ``in_channels``.
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        self._center = center
        self._scale = scale
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x):
        channels = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            p.shape = (channels,)

    def cast(self, dtype):
        if np.dtype(dtype).name in ("float16", "bfloat16"):
            dtype = "float32"  # BN statistics stay fp32 (reference behavior)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **self._kwargs)

    def __repr__(self):
        return (f"BatchNorm(axis={self._axis}, "
                f"in_channels={self.gamma.shape[0]})")


class LayerNorm(HybridBlock):
    """Layer normalization over the last (or given) axis."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x):
        channels = x.shape[self._axis]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)

    def __repr__(self):
        return f"LayerNorm(axis={self._axis}, eps={self._epsilon})"


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x):
        channels = x.shape[self._axis]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class GroupNorm(HybridBlock):
    """Group normalization (parity: reference nn.GroupNorm over the
    GroupNorm op)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True,
                 scale=True, beta_initializer="zeros",
                 gamma_initializer="ones", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            # per-GROUP scale/shift, the reference parameter layout
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(num_groups,), init=gamma_initializer)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(num_groups,), init=beta_initializer)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta,
                           num_groups=self._num_groups,
                           eps=self._epsilon)


class Embedding(HybridBlock):
    """Index → dense vector lookup (parity: nn.Embedding).

    ``sparse_grad=True`` types the weight's gradient as ``row_sparse``
    so optimizers take the lazy touched-rows-only update path — the
    reference's sparse-embedding training story.  Storage stays a dense
    XLA buffer (gather/scatter ride the MXU-friendly path)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return x


class Lambda(Block):
    """Wrap a function (or nd-function name) as a Block."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd_mod
            assert hasattr(nd_mod, function), \
                f"Function name {function!r} is not found in ndarray."
            self._func_impl = getattr(nd_mod, function)
        else:
            self._func_impl = function

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"Lambda({getattr(self._func_impl, '__name__', '?')})"


class HybridLambda(HybridBlock):
    """Wrap a function (F, x) -> y as a HybridBlock."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function

            def _fn(F, *args):
                return getattr(F, function)(*args)
            self._func = _fn
        else:
            self._func = function
            self._func_name = getattr(function, "__name__", "custom")

    def hybrid_forward(self, F, *args):
        return self._func(F, *args)

    def __repr__(self):
        return f"HybridLambda({self._func_name})"


class HybridConcatenate(HybridBlock):
    """Run children on the same input, concat outputs along ``axis``."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Concatenate(Block):
    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        from ... import ndarray as nd_mod
        out = [block(x) for block in self._children.values()]
        return nd_mod.concat(*out, dim=self.axis)
