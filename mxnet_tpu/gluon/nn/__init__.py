"""``mx.gluon.nn`` namespace (parity: python/mxnet/gluon/nn/)."""
from .basic_layers import (Sequential, HybridSequential, Dense, Dropout,
                           BatchNorm, LayerNorm, InstanceNorm, GroupNorm,
                           Embedding,
                           Flatten, Lambda, HybridLambda, HybridConcatenate,
                           Concatenate, Identity)
from .activations import (Activation, LeakyReLU, PReLU, ELU, SELU, Swish,
                          GELU, SiLU)
from .conv_layers import (Conv1D, Conv2D, Conv3D, Conv1DTranspose,
                          Conv2DTranspose, Conv3DTranspose,
                          MaxPool1D, MaxPool2D, MaxPool3D,
                          AvgPool1D, AvgPool2D, AvgPool3D,
                          GlobalMaxPool1D, GlobalMaxPool2D, GlobalMaxPool3D,
                          GlobalAvgPool1D, GlobalAvgPool2D, GlobalAvgPool3D,
                          ReflectionPad2D)

__all__ = [
    "Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
    "LayerNorm", "InstanceNorm", "GroupNorm", "Embedding",
    "Flatten", "Lambda",
    "HybridLambda", "HybridConcatenate", "Concatenate", "Identity",
    "Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "Swish", "GELU",
    "SiLU",
    "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
    "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D",
    "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D", "GlobalMaxPool2D",
    "GlobalMaxPool3D", "GlobalAvgPool1D", "GlobalAvgPool2D",
    "GlobalAvgPool3D", "ReflectionPad2D",
]
