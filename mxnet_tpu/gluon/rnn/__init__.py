"""``mx.gluon.rnn``: recurrent cells and fused layers (SURVEY.md §2.2
RNN ops, §2.5 Gluon core)."""
from .rnn_cell import (RecurrentCell, HybridRecurrentCell, RNNCell,
                       LSTMCell, GRUCell, SequentialRNNCell,
                       HybridSequentialRNNCell, DropoutCell, ResidualCell,
                       BidirectionalCell, ZoneoutCell)
from .rnn_layer import RNN, LSTM, GRU

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ResidualCell", "BidirectionalCell",
           "ZoneoutCell", "RNN", "LSTM", "GRU"]
