"""Recurrent cells (parity: ``python/mxnet/gluon/rnn/rnn_cell.py``).

Cell-level API: explicit per-step state, ``unroll`` for fixed-length
static unrolling (hybridizable — the unrolled graph fuses under XLA), and
modifier/composite cells.  Gate orders match the reference: LSTM
``[i, f, c, o]``, GRU ``[r, z, n]``.
"""
from __future__ import annotations

from ... import ndarray as nd
from ...base import MXNetError
from ..block import HybridBlock
from ..nn.activations import Activation

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ResidualCell", "BidirectionalCell",
           "ZoneoutCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Normalize inputs to a list of per-step arrays (or merged tensor)."""
    axis = layout.find("T")
    if isinstance(inputs, (list, tuple)):
        in_list = list(inputs)
        if length is not None and len(in_list) != length:
            raise MXNetError(f"unroll: expected {length} steps, got "
                             f"{len(in_list)}")
        return in_list, axis
    if axis != 0:
        inputs = inputs.swapaxes(0, axis)
    steps = inputs.shape[0]
    if length is not None and steps != length:
        raise MXNetError(f"unroll: expected length {length}, data has "
                         f"{steps}")
    return [inputs[i] for i in range(steps)], axis


def _merge_outputs(outputs, axis):
    stacked = nd.stack(*outputs, axis=0)
    if axis != 0:
        stacked = stacked.swapaxes(0, axis)
    return stacked


class RecurrentCell(HybridBlock):
    """Base class for rnn cells."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called "\
            "directly. Call the modifier cell instead."
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape, **dict(info, **kwargs)))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Static unroll: a Python loop the compiler fuses (parity:
        RecurrentCell.unroll)."""
        self.reset()
        in_list, axis = _format_sequence(length, inputs, layout, False)
        batch_size = in_list[0].shape[0]
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size,
                                           ctx=in_list[0].context)
        states = begin_state
        outputs = []
        for i in range(length):
            out, states = self(in_list[i], states)
            outputs.append(out)
        if valid_length is not None:
            stacked = nd.stack(*outputs, axis=0)
            masked = nd.SequenceMask(stacked, valid_length,
                                     use_sequence_length=True)
            outputs = [masked[i] for i in range(length)]
        if merge_outputs:
            return _merge_outputs(outputs, axis), states
        return outputs, states

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)

    def _alias(self):
        return "rnn"


class HybridRecurrentCell(RecurrentCell):
    pass


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: h' = act(W_ih x + b_ih + W_hh h + b_hh)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def infer_shape(self, inputs, states):
        self.i2h_weight.shape = (self._hidden_size, inputs.shape[-1])

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]

    def _deferred_infer_shape(self, inputs, states):
        self.infer_shape(inputs, states)


class LSTMCell(HybridRecurrentCell):
    """LSTM cell; gates ordered [i, f, c, o] (reference order)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, activation="tanh",
                 recurrent_activation="sigmoid", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._activation = activation
        self._recurrent_activation = recurrent_activation
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def infer_shape(self, inputs, states):
        self.i2h_weight.shape = (4 * self._hidden_size, inputs.shape[-1])

    def _deferred_infer_shape(self, inputs, states):
        self.infer_shape(inputs, states)

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.split(gates, num_outputs=4, axis=1)
        in_gate = F.Activation(slices[0],
                               act_type=self._recurrent_activation)
        forget_gate = F.Activation(slices[1],
                                   act_type=self._recurrent_activation)
        in_transform = F.Activation(slices[2], act_type=self._activation)
        out_gate = F.Activation(slices[3],
                                act_type=self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c,
                                         act_type=self._activation)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell; gates ordered [r, z, n] (reference order)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def infer_shape(self, inputs, states):
        self.i2h_weight.shape = (3 * self._hidden_size, inputs.shape[-1])

    def _deferred_infer_shape(self, inputs, states):
        self.infer_shape(inputs, states)

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=1)
        reset = F.sigmoid(i2h_r + h2h_r)
        update = F.sigmoid(i2h_z + h2h_z)
        new = F.tanh(i2h_n + reset * h2h_n)
        next_h = (1.0 - update) * new + update * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells sequentially (parity: SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def __len__(self):
        return len(self._children)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        in_list, axis = _format_sequence(length, inputs, layout, False)
        batch_size = in_list[0].shape[0]
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size,
                                           ctx=in_list[0].context)
        p = 0
        next_states = []
        cells = list(self._children.values())
        for i, cell in enumerate(cells):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < len(cells) - 1
                else merge_outputs, valid_length=valid_length)
            next_states.extend(states)
        return inputs, next_states


HybridSequentialRNNCell = SequentialRNNCell


class ModifierCell(HybridRecurrentCell):
    def __init__(self, base_cell):
        assert not base_cell._modified
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell
        self.register_child(base_cell)

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs) \
            if func is not None else self.base_cell.begin_state(**kwargs)
        self.base_cell._modified = True
        return begin


class DropoutCell(HybridRecurrentCell):
    """Applies dropout on input (parity: DropoutCell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ResidualCell(ModifierCell):
    """Adds residual connection around the base cell."""

    def __call__(self, inputs, states):
        self.base_cell._modified = False
        output, states = self.base_cell(inputs, states)
        self.base_cell._modified = True
        return output + inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs, begin_state=begin_state, layout=layout,
            merge_outputs=False, valid_length=valid_length)
        self.base_cell._modified = True
        in_list, axis = _format_sequence(length, inputs, layout, False)
        outputs = [o + x for o, x in zip(outputs, in_list)]
        if merge_outputs:
            return _merge_outputs(outputs, axis), states
        return outputs, states


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (parity: ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell)
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, inputs, states):
        from ... import autograd
        cell = self.base_cell
        cell._modified = False
        next_output, next_states = cell(inputs, states)
        cell._modified = True
        if not autograd.is_training():
            return next_output, next_states

        def mask(p, like):
            return nd.random.uniform(0, 1, shape=like.shape,
                                     ctx=like.context) < p

        prev_output = self._prev_output
        if prev_output is None:
            prev_output = nd.zeros(next_output.shape,
                                   ctx=next_output.context)
        out = nd.where(mask(self.zoneout_outputs, next_output),
                       prev_output, next_output) \
            if self.zoneout_outputs > 0 else next_output
        new_states = [nd.where(mask(self.zoneout_states, ns), os, ns)
                      if self.zoneout_states > 0 else ns
                      for ns, os in zip(next_states, states)]
        self._prev_output = out
        return out, new_states


class BidirectionalCell(HybridRecurrentCell):
    """Runs l_cell forward and r_cell backward over the sequence."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped — use "
                        "unroll()")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        in_list, axis = _format_sequence(length, inputs, layout, False)
        batch_size = in_list[0].shape[0]
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size,
                                           ctx=in_list[0].context)
        cells = list(self._children.values())
        l_cell, r_cell = cells[0], cells[1]
        n_l = len(l_cell.state_info())

        def _reverse(seq_list):
            """Reverse the time axis; with valid_length, reverse only the
            valid prefix per sequence (parity: SequenceReverse with
            sequence_length) so the backward cell starts on real data,
            not padding."""
            if valid_length is None:
                return list(reversed(seq_list))
            vl = valid_length if isinstance(valid_length, nd.NDArray) \
                else nd.array(valid_length)
            stacked = nd.stack(*seq_list, axis=0)
            rev = nd.SequenceReverse(stacked, vl,
                                     use_sequence_length=True)
            return [rev[i] for i in range(len(seq_list))]

        l_outputs, l_states = l_cell.unroll(
            length, in_list, begin_state=begin_state[:n_l],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, _reverse(in_list),
            begin_state=begin_state[n_l:], layout=layout,
            merge_outputs=False, valid_length=valid_length)
        r_outputs = _reverse(r_outputs)
        outputs = [nd.concat(l, r, dim=1)
                   for l, r in zip(l_outputs, r_outputs)]
        if merge_outputs:
            return _merge_outputs(outputs, axis), l_states + r_states
        return outputs, l_states + r_states
