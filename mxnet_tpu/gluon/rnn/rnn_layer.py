"""Fused recurrent layers RNN/LSTM/GRU (parity:
``python/mxnet/gluon/rnn/rnn_layer.py`` over the cuDNN-fused ``src/
operator/rnn*`` — SURVEY.md §2.2 "RNN ops").

TPU-native design: the input projection ``x·W_i2hᵀ`` for ALL timesteps is
ONE large matmul (MXU-shaped), then only the recurrent half scans via
``lax.scan`` (contrib.foreach).  This is the same split the cuDNN fused
kernels use, expressed in the compiler's vocabulary instead of a
hand-fused kernel.  Multi-layer and bidirectional stack/concat exactly
like the reference; param names (``l0_i2h_weight``, ``r0_h2h_bias``…)
match so checkpoints map 1:1.
"""
from __future__ import annotations

from ... import ndarray as nd
from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, gates, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout {layout}; must be TNC or NTC"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = gates

        ng, ni, nh = gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in (["l", "r"] if bidirectional else ["l"]):
                    self._register_param(f"{j}{i}_i2h_weight",
                                         (ng * nh, ni))
                    self._register_param(f"{j}{i}_h2h_weight",
                                         (ng * nh, nh))
                    self._register_param(f"{j}{i}_i2h_bias", (ng * nh,))
                    self._register_param(f"{j}{i}_h2h_bias", (ng * nh,))
                ni = nh * self._dir

    def _register_param(self, name, shape):
        p = self.params.get(name, shape=shape, allow_deferred_init=True)
        setattr(self, name, p)  # __setattr__ registers into _reg_params

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape, **dict(info, **kwargs)))
        return states

    def infer_shape(self, inputs, *args):
        ni = inputs.shape[2] if self._layout == "TNC" else inputs.shape[2]
        ng, nh = self._gates, self._hidden_size
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                getattr(self, f"{j}{i}_i2h_weight").shape = (ng * nh, ni)
            ni = nh * self._dir

    def _deferred_infer_shape(self, *args):
        self.infer_shape(*args)

    def __call__(self, inputs, states=None):
        return super().__call__(inputs, states)

    def hybrid_forward(self, F, inputs, states, **params):
        explicit_states = states is not None
        x = inputs
        if self._layout == "NTC":
            x = x.swapaxes(0, 1)  # internal compute is time-major
        batch_size = x.shape[1]
        if states is None:
            states = self.begin_state(batch_size, ctx=inputs.context)
        if not isinstance(states, (list, tuple)):
            states = [states]

        outputs, out_states = self._forward_kernel(F, x, list(states),
                                                   params)
        if self._layout == "NTC":
            outputs = outputs.swapaxes(0, 1)
        if explicit_states:
            return outputs, out_states
        return outputs

    # per-subclass: single-direction scan over one layer
    def _layer_scan(self, F, proj, h2h_weight, h2h_bias, init_states):
        raise NotImplementedError

    def _forward_kernel(self, F, x, states, params):
        """states: list of (num_layers*dir, N, H) arrays."""
        ns = len(self.state_info())
        layer_in = x
        out_state_slices = [[] for _ in range(ns)]
        for i in range(self._num_layers):
            dir_outs = []
            for d, j in enumerate(["l", "r"][:self._dir]):
                w_i2h = params[f"{j}{i}_i2h_weight"]
                w_h2h = params[f"{j}{i}_h2h_weight"]
                b_i2h = params[f"{j}{i}_i2h_bias"]
                b_h2h = params[f"{j}{i}_h2h_bias"]
                seq = layer_in if d == 0 else F.reverse(layer_in, axis=0)
                # ONE big input projection across all timesteps (MXU)
                T, N = seq.shape[0], seq.shape[1]
                flat = seq.reshape((T * N, -1))
                proj = F.FullyConnected(
                    flat, w_i2h, b_i2h,
                    num_hidden=self._gates * self._hidden_size)
                proj = proj.reshape((T, N,
                                     self._gates * self._hidden_size))
                idx = i * self._dir + d
                init = [s[idx] for s in states]
                outs, finals = self._layer_scan(F, proj, w_h2h, b_h2h,
                                                init)
                if d == 1:
                    outs = F.reverse(outs, axis=0)
                dir_outs.append(outs)
                for k, fs in enumerate(finals):
                    out_state_slices[k].append(fs)
            layer_out = dir_outs[0] if self._dir == 1 else \
                F.concat(dir_outs[0], dir_outs[1], dim=2)
            if self._dropout and i < self._num_layers - 1:
                layer_out = F.Dropout(layer_out, p=self._dropout)
            layer_in = layer_out
        out_states = [F.stack(*slices, axis=0)
                      for slices in out_state_slices]
        return layer_in, out_states


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (parity: gluon.rnn.RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 input_size=0, **kwargs):
        self._activation = activation
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, gates=1, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]

    def _layer_scan(self, F, proj, w_h2h, b_h2h, init):
        act = self._activation
        nh = self._hidden_size

        def body(xt, h):
            h_new = F.Activation(
                xt + F.FullyConnected(h, w_h2h, b_h2h, num_hidden=nh),
                act_type=act)
            return h_new, h_new

        outs, final_h = F.contrib.foreach(body, proj, init[0])
        return outs, [final_h]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (parity: gluon.rnn.LSTM); states [h, c]."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, gates=4, **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size,
                 self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]

    def _layer_scan(self, F, proj, w_h2h, b_h2h, init):
        nh = self._hidden_size

        def body(xt, hc):
            h, c = hc
            gates = xt + F.FullyConnected(h, w_h2h, b_h2h,
                                          num_hidden=4 * nh)
            ig, fg, cg, og = F.split(gates, num_outputs=4, axis=1)
            i_t = F.sigmoid(ig)
            f_t = F.sigmoid(fg)
            c_t = f_t * c + i_t * F.tanh(cg)
            h_t = F.sigmoid(og) * F.tanh(c_t)
            return h_t, [h_t, c_t]

        outs, finals = F.contrib.foreach(body, proj, init)
        return outs, finals


class GRU(_RNNLayer):
    """Multi-layer GRU (parity: gluon.rnn.GRU); gate order [r, z, n]."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, gates=3, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]

    def _layer_scan(self, F, proj, w_h2h, b_h2h, init):
        nh = self._hidden_size

        def body(xt, h):
            h2h = F.FullyConnected(h, w_h2h, b_h2h, num_hidden=3 * nh)
            i_r, i_z, i_n = F.split(xt, num_outputs=3, axis=1)
            h_r, h_z, h_n = F.split(h2h, num_outputs=3, axis=1)
            r = F.sigmoid(i_r + h_r)
            z = F.sigmoid(i_z + h_z)
            n = F.tanh(i_n + r * h_n)
            h_new = (1.0 - z) * n + z * h
            return h_new, h_new

        outs, final_h = F.contrib.foreach(body, proj, init[0])
        return outs, [final_h]
