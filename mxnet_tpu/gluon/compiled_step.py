"""CompiledStep: the whole Gluon training step as ONE device dispatch.

PR 2 collapsed the optimizer into one dispatch; this module collapses
the REST of the step.  A hybridized ``HybridBlock`` forward still runs
one compiled program per op, ``autograd.backward`` replays one vjp per
recorded node, and only then does the fused optimizer program run — on
a remote PJRT tunnel every one of those dispatches is a full RPC round
trip (~30 ms measured), so a 50-op forward is pure overhead.
``CompiledStep`` traces forward + loss + backward + the optimizer's
fused multi-tensor program into a single donated XLA executable:

    (params, states, scalars, inputs, label, key)
        -> (loss, new_params, new_states, aux)

Mechanics (the same seams ``CachedOp`` and ``parallel.trainer`` use):

* the block's imperative forward runs under ``tracing_scope`` (the
  CachedOp export-trace seam) with parameter buffers swapped for traced
  values; gradients come from ``jax.value_and_grad`` of the loss SUM —
  exactly the ones-cotangent ``loss.backward()`` applies;
* parameter mutation inside forward (BatchNorm running stats) is
  functionalized by version-drift detection and returned as ``aux``
  outputs, written back after the dispatch;
* dropout RNG is a per-step base-key INPUT + the same per-request
  ``fold_in`` scheme as CachedOp, so masks match the eager hybridized
  path bit-for-bit and fresh keys never retrace;
* the optimizer update is the registered ``multi_*`` program from
  ``Optimizer._fused_plan`` spliced into the trace; its per-step host
  scalars (lr schedule / wd / Adam bias correction / rescale_grad) ride
  as ARRAY INPUTS via ``fused_step_scalars`` — schedulers never
  recompile.  Static attrs (momentum, betas, clip bounds) ARE baked;
  the plan attrs are re-derived every step and a drift evicts the stale
  executable (``engine.drop_cached``) instead of applying old values;
* trainable-weight and optimizer-state buffers are DONATED — a
  BERT-sized step does not double live HBM.  The donation contract and
  failure protocol (poisoning after a post-donation failure) mirror the
  fused optimizer and SPMD trainer;
* ``step_multi(K)`` bulks K real optimizer steps into one dispatch via
  ``lax.scan`` with params+states as the carry — K-step schedules, RNG
  keys, and Adam bias correction are threaded per inner step, so the
  result is bit-identical to K ``step()`` calls.

Entry point: ``trainer.compile_step(net, loss_fn)``.  The escape hatch
``MXTPU_COMPILED_STEP=0`` and any ineligibility (non-hybridizable
forward, optimizer without a fused program, distributed kvstore,
``grad_req='add'``, …) fall back TRANSPARENTLY to the eager
record/backward/step path; silent fallbacks are recorded in a module
registry that mxlint surfaces as MXL305 findings (the finding carries
the reason).  See docs/compiled_step.md.
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from . import block as block_mod

__all__ = ["CompiledStep", "fallback_reports", "clear_fallback_reports"]


# -- silent-fallback registry (read by mxlint's MXL305 runtime pass) -------
_fallback_log: List[Tuple[str, str]] = []
_fallback_lock = threading.Lock()


def fallback_reports() -> List[Tuple[str, str]]:
    """``[(step_name, reason), ...]`` for every CompiledStep that
    silently degraded to the eager path this process.  The explicit
    ``MXTPU_COMPILED_STEP=0`` escape hatch is NOT recorded — the user
    asked for eager; only surprising degradations are findings."""
    with _fallback_lock:
        return list(_fallback_log)


def clear_fallback_reports():
    with _fallback_lock:
        _fallback_log.clear()


def _record_fallback(name: str, reason: str):
    with _fallback_lock:
        _fallback_log.append((name, reason))


def _flatten_state(state, out: List[NDArray]):
    """Flat NDArray leaves of an updater state tree (None leaves skipped
    — they carry no buffer and rebuild positionally)."""
    if state is None:
        return
    if isinstance(state, NDArray):
        out.append(state)
        return
    if isinstance(state, (list, tuple)):
        for s in state:
            _flatten_state(s, out)
        return
    raise MXNetError(f"unsupported optimizer state leaf: {type(state)}")


def _rebuild_state(template, leaves_iter):
    """Rebuild a state tree in the template's structure, drawing leaves
    (in ``_flatten_state`` order) from ``leaves_iter``."""
    if template is None:
        return None
    if isinstance(template, NDArray):
        return next(leaves_iter)
    return tuple(_rebuild_state(t, leaves_iter) for t in template)


class CompiledStep:
    """One-dispatch train step for ``(net, loss_fn, trainer)``.

    Build via ``trainer.compile_step(net, loss_fn)``.  ``step(data,
    label, batch_size=None)`` runs forward+backward+update as one
    donated dispatch and returns the (unreduced) loss; ``step_multi``
    runs K steps per dispatch.  ``last_path`` reports which path the
    previous call took (``"compiled"`` / ``"eager"``) and
    ``fallback_reason`` the sticky degradation reason, if any.
    """

    # atomic (GIL-safe) id mint: the uid lands in the engine cache KEY,
    # and two steps sharing a name would silently run each other's
    # traced program
    _uid = __import__("itertools").count(1)

    def __init__(self, net, loss_fn: Callable, trainer):
        self.net = net
        self.loss_fn = loss_fn
        self.trainer = trainer
        self.name = f"gluon_train_step_{net.name}_{next(CompiledStep._uid)}"
        self._setup_done = False
        self._params = None
        self._tr_idx: List[int] = []
        self.fallback_reason: Optional[str] = None
        self.last_path: Optional[str] = None
        self._poisoned: Optional[str] = None
        # trace-time structure (populated while jax traces _core)
        self._mutated_idx: List[int] = []
        self._core = None
        self._core_shape = None
        self._sig = None
        self._active_names = {self.name}
        # persistent-tier identity + AOT warm-start bookkeeping
        # (docs/compile_cache.md): the engine-cache name above is
        # uid-suffixed (process-scoped), so persistent entries key on a
        # STABLE name derived from the net + a structural hash; a
        # warm-start manifest pins the name recorded at save time so
        # auto-naming drift cannot orphan the entries
        self._persist_base: Optional[str] = None
        self._persist_pinned = False
        self._struct_hash: Optional[str] = None
        # set the first time _core actually TRACES in this process — a
        # persistent-tier hit skips the trace, and with it the
        # mutated_idx discovery the aux write-back routing needs
        self._trace_seen = [False]
        self._dims = None                 # (P, S, C, n_args) at save
        self._variants = {}               # manifest rows per variant
        self.warm_started = False
        # training-health plane (telemetry.health): the spec describes
        # the extra in-graph stats vector the traced program returns
        # (None = plane off, program unchanged); the counter drives
        # MXTPU_HEALTH_EVERY sampling; health_manager arms the
        # rollback action (recover(manager) on a bad verdict)
        self._health_spec = None
        self._health_count = 0
        self.health_manager = None
        # MXTPU_ZERO_STAGE visibility latch (docs/zero.md): the ZeRO
        # sharded update is an SPMD-trainer feature — a single-context
        # CompiledStep has no dp axis to shard over, and silently
        # ignoring the env var would read as "memory didn't drop".
        # One retained event per step object says why.
        self._zero_noted = False
        self._integrity_noted = False

    # -- public API -------------------------------------------------------
    def step(self, data, label, batch_size=None):
        """ONE training step; returns the loss NDArray (unreduced, like
        the eager ``loss_fn`` output).  ``batch_size`` defaults to the
        leading dimension of ``label`` and folds into ``rescale_grad``
        as a dynamic scalar (parity: ``Trainer.step(batch_size)``)."""
        from .. import profiler
        from .. import engine, telemetry
        import time
        args, label = self._coerce(data, label)
        if batch_size is None:
            batch_size = label.shape[0] if label.shape else \
                args[0].shape[0]
        with profiler._span(f"CompiledStep[{self.net.name}]",
                            "compiled_step") as sp, \
                telemetry.step_owner(self, "compiled_step"):
            t0 = time.perf_counter()
            d0 = engine.dispatch_count()
            out = self._step_or_fallback(args, label, batch_size)
            sp.sync(out._data)
            telemetry.record_step(
                "compiled_step", time.perf_counter() - t0,
                dispatches=engine.dispatch_count() - d0,
                examples=batch_size, path=self.last_path)
            return out

    def step_multi(self, data, label, batch_size=None, repeat=None):
        """K optimizer steps as ONE dispatch; returns the (K, ...)
        per-step losses.

        Without ``repeat``: ``data``/``label`` carry a leading K dim and
        inner step k consumes slice k.  With ``repeat=K``: single-batch
        ``data``/``label`` are reused for every inner step WITHOUT
        materializing K host copies (the batch is an ordinary program
        input the scan body closes over).  Per-inner-step RNG keys and
        optimizer scalars (schedules, Adam bias correction) are
        threaded, so K bulked steps are bit-identical to K ``step()``
        calls.
        """
        from .. import profiler
        args, label = self._coerce(data, label)
        if repeat is not None:
            k_steps = int(repeat)
            if k_steps <= 0:
                raise MXNetError(f"repeat must be positive, got {repeat}")
        else:
            k_steps = args[0].shape[0]
            if label.shape[0] != k_steps:
                raise MXNetError(
                    f"step_multi: label leading dim {label.shape[0]} != "
                    f"data leading dim {k_steps}")
        if batch_size is None:
            # per-inner-step batch dim, matching step()'s fallback
            # (label first, then data — never a feature dim)
            lshape = label.shape if repeat is not None else \
                label.shape[1:]
            dshape = args[0].shape if repeat is not None else \
                args[0].shape[1:]
            batch_size = lshape[0] if lshape else (
                dshape[0] if dshape else 1)
        from .. import engine, telemetry
        import time
        with profiler._span(f"CompiledStep[{self.net.name}].multi",
                            "compiled_step_multi") as sp, \
                telemetry.step_owner(self, "compiled_step_multi"):
            t0 = time.perf_counter()
            d0 = engine.dispatch_count()
            out = self._step_or_fallback(args, label, batch_size,
                                         k_steps=k_steps,
                                         repeat=repeat is not None)
            sp.sync(out._data)
            telemetry.record_step(
                "compiled_step", time.perf_counter() - t0,
                dispatches=engine.dispatch_count() - d0,
                examples=batch_size * k_steps, path=self.last_path,
                steps=k_steps)
            return out

    # -- AOT warm-start (docs/compile_cache.md) ---------------------------
    def save_signature(self, path: str) -> str:
        """Write this step's warm-start manifest: input avals, donation
        layout, structural hash, persistent-tier identity, and the aux
        write-back routing for every compiled variant.  A fresh process
        (same model/optimizer construction) feeds it to
        :meth:`warm_start` / ``Trainer.warm_start`` to precompile the
        whole fused train program before the first batch arrives.
        Requires at least one successful compiled ``step()`` /
        ``step_multi()``; returns ``path``."""
        import json
        from .. import engine
        if not self._variants or self._sig is None:
            raise MXNetError(
                "save_signature: run at least one successful compiled "
                "step() first (last_path must be 'compiled')")
        P, S, C, n_args = self._dims
        manifest = {
            "format": 1, "kind": "gluon_compiled_step",
            "fingerprint": engine.persist.fingerprint(),
            "net": self.net.name, "loss": type(self.loss_fn).__name__,
            "persist_base": self._persist_base,
            "struct_hash": self._struct_hash,
            "P": P, "S": S, "C": C, "n_args": n_args,
            "tr_idx": [int(i) for i in self._tr_idx],
            "mutated_idx": [int(i) for i in self._mutated_idx],
            "variants": [self._variants[k]
                         for k in sorted(self._variants)],
        }
        tmp = path + f".tmp{__import__('os').getpid()}"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        __import__("os").replace(tmp, path)
        return path

    def warm_start(self, path: str) -> bool:
        """Precompile every variant recorded in a
        :meth:`save_signature` manifest — persistent-tier reload when
        the cache dir holds the executables (no trace, no compile), a
        fresh AOT compile otherwise — so the FIRST batch dispatches a
        ready program.  Overlap it with DataLoader spin-up for
        near-zero time-to-first-step across restarts.

        Never raises for a bad/mismatched manifest: returns False (and
        records a ``warm_start`` telemetry event with the reason), and
        the step simply compiles on first use as it always did.
        """
        import json
        import numpy as np
        from .. import engine, telemetry
        from .. import ndarray as nd

        def _fail(reason):
            telemetry.record_event("warm_start", name=self.name,
                                   ok=False, reason=reason)
            return False

        try:
            with open(path) as f:
                m = json.load(f)
        except (OSError, ValueError) as e:
            return _fail(f"unreadable manifest: {e!r}"[:300])
        if m.get("kind") != "gluon_compiled_step" or \
                m.get("format") != 1:
            return _fail("not a gluon_compiled_step manifest")
        if m.get("fingerprint") != engine.persist.fingerprint():
            return _fail("environment fingerprint mismatch "
                         "(jax/jaxlib/platform/salt)")
        if self._poisoned is not None:
            return _fail("step is poisoned")
        try:
            P, S, C = int(m["P"]), int(m["S"]), int(m["C"])
            n_args = int(m["n_args"])
            variants = list(m["variants"])
            base = m["persist_base"]
        except (KeyError, TypeError, ValueError) as e:
            return _fail(f"malformed manifest: {e!r}"[:300])
        if not variants:
            return _fail("manifest has no compiled variants")

        # dummy inputs at the recorded avals drive the SAME setup the
        # first real step would run (deferred-shape resolution included)
        try:
            single = min(variants, key=lambda v: bool(v["k_steps"]))
            avals = engine.persist.sig_from_json(single["avals"])
            in_avals = avals[P + S + C:P + S + C + n_args]
            if any(len(a) != 2 for a in in_avals):
                return _fail("non-array input aval in manifest")
            shapes = [a[0] for a in in_avals]
            if single.get("k_steps") and not single.get("repeat"):
                # a bulked variant's inputs carry the K dim; setup
                # wants per-step shapes (same slice _step_or_fallback
                # takes)
                shapes = [s[1:] for s in shapes]
            args = [nd.array(np.zeros(s, dtype=np.dtype(a[1])))
                    for s, a in zip(shapes, in_avals)]
        except Exception as e:
            return _fail(f"bad aval record: {e!r}"[:300])
        try:
            if not self._setup_done:
                self._setup(args)
            reason = self._eligibility()
            if reason is not None:
                return _fail(
                    f"ineligible for the compiled path: {reason}")
            try:
                self._check_sig(len(self._state_leaves()), n_args)
            except _TraceFallback as e:
                return _fail(str(e))
            if self._struct_hash != m.get("struct_hash"):
                return _fail("structural hash mismatch: the manifest "
                             "describes a different net/optimizer "
                             "configuration")
            # adopt the save-time identity: persistent entries were
            # keyed under it, and gluon auto-naming may have drifted
            self._persist_base = base
            self._persist_pinned = True
            self._mutated_idx[:] = [int(i) for i in m["mutated_idx"]]
            self._trace_seen[0] = True
            self._dims = (P, S, C, n_args)

            import jax
            ctx = self._params[0].data().context if self._params \
                else None
            sources = {}
            for v in variants:
                try:
                    sds = [jax.ShapeDtypeStruct(a[0], np.dtype(a[1]))
                           for a in engine.persist.sig_from_json(
                               v["avals"])]
                except (TypeError, ValueError) as e:
                    return _fail(f"bad variant avals: {e!r}"[:300])
                k = v.get("k_steps")
                hon = bool(v.get("health_out"))
                core = self._get_core(P, S, C, n_args, ctx,
                                      health_on=hon)
                if k:
                    pure = self._make_pure_k(
                        core, P, S, C, n_args, int(k),
                        bool(v.get("repeat")), health_on=hon,
                        with_due=hon and
                        str(v["suffix"]).endswith("_hs"))
                else:
                    pure = self._make_pure(core, P, S, C)
                name = self.name + v["suffix"]
                self._active_names.add(name)
                sources[name] = engine.aot_compile(
                    name, pure, {}, sds, donate=tuple(v["donate"]),
                    persist_name=base + v["suffix"])
                self._variants[(int(k or 0),
                                bool(v.get("repeat")), hon)] = v
        except Exception as e:
            # the never-raises contract: a stale manifest (e.g. wrong
            # input widths feeding deferred-shape init) degrades to
            # the cold-compile path, not a crash
            return _fail(f"warm-start failed: {e!r}"[:300])
        self.warm_started = True
        telemetry.record_event("warm_start", name=self.name, ok=True,
                               sources=sources)
        return True

    # -- elastic protocol (docs/elasticity.md) ----------------------------
    def _elastic_export(self):
        """Checkpoint payload (``elastic.CheckpointManager``): the
        trainer's params + optimizer-state leaves + counters, plus
        this step's persistent-tier identity so a restored process can
        warm-start under the same name."""
        payload = self.trainer._elastic_export()
        payload["persist_name"] = self._persist_base
        return payload

    def _elastic_restore(self, payload):
        self.trainer._elastic_restore(payload)
        self._poisoned = None

    def recover(self, manager, step: Optional[int] = None) -> int:
        """Rebuild the donated weight/optimizer-state buffers from the
        last committed checkpoint (or ``step``) and clear the poison
        latch — after this the step dispatches again.  Safe on a
        healthy step too (plain restore).  Returns the restored step.
        Recovery FORKS the timeline: checkpoints newer than the
        restored step are invalidated, so a later crash can never
        resume from the abandoned run."""
        from ..elastic.manager import timed_recover
        return timed_recover(manager, self, "compiled_step",
                             step=step, name=self.name,
                             was_poisoned=self._poisoned is not None)

    # -- path selection ---------------------------------------------------
    def _coerce(self, data, label):
        from .. import ndarray as nd
        args = list(data) if isinstance(data, (list, tuple)) else [data]
        args = [a if isinstance(a, NDArray)
                else nd.array(np.asarray(a), dtype=np.asarray(a).dtype)
                for a in args]
        if not isinstance(label, NDArray):
            label = nd.array(np.asarray(label),
                             dtype=np.asarray(label).dtype)
        return args, label

    def _step_or_fallback(self, args, label, batch_size, k_steps=None,
                          repeat=False):
        from .. import envs
        if self._poisoned is not None:
            from .. import engine as _eng
            if _eng._san is not None:
                # mxsan MXL703: a poisoned owner stepped without
                # recover() — the finding is the audit trail; the
                # raise below is unchanged
                _eng._san.note_poisoned_step(self, "compiled_step",
                                             self._poisoned)
            raise MXNetError(
                "this CompiledStep's weight/optimizer-state buffers were "
                "donated to a dispatch that failed and are no longer "
                "valid; call recover(manager) to restore from the last "
                "committed checkpoint (docs/elasticity.md). "
                f"Original error: {self._poisoned}")
        if not envs.get("MXTPU_COMPILED_STEP"):
            # explicit escape hatch: eager, but NOT a silent fallback
            return self._eager(args, label, batch_size, k_steps, repeat)
        if self.fallback_reason is not None:
            return self._eager(args, label, batch_size, k_steps, repeat)
        if not self._setup_done:
            self._setup(args if k_steps is None or repeat
                        else [a[0] for a in args])
        reason = self._eligibility()
        if reason is not None:
            self._fall_back(reason)
            return self._eager(args, label, batch_size, k_steps, repeat)
        try:
            return self._dispatch(args, label, batch_size, k_steps,
                                  repeat)
        except _TraceFallback as e:
            self._fall_back(str(e))
            return self._eager(args, label, batch_size, k_steps, repeat)

    def _fall_back(self, reason: str):
        from .. import telemetry
        self.fallback_reason = reason
        _record_fallback(self.name, reason)
        telemetry.counter("mxtpu_fallbacks_total",
                          "silent compiled->eager degradations").inc()
        telemetry.record_event("fallback", where="compiled_step",
                               name=self.name, reason=reason)

    # -- setup / eligibility ----------------------------------------------
    def _setup(self, args):
        from .. import autograd
        tr = self.trainer
        params = list(tr._params)
        if any(p._deferred_init for p in params):
            # one IMPERATIVE warm-up resolves every deferred shape —
            # _call_unhybridized, exactly like CachedOp's warm-up, so
            # the global RNG stream advances by the same draws as the
            # eager hybridized path's first call (a full net() here
            # would run CachedOp and consume one extra base key,
            # desynchronizing dropout masks from the eager path)
            with autograd.pause():
                if hasattr(self.net, "_call_unhybridized"):
                    self.net._call_unhybridized(*args)
                else:
                    self.net(*args)
        self._params = params
        self._tr_idx = [i for i, p in enumerate(params)
                        if p.grad_req != "null"]
        tr._optimizer._set_current_context(0)
        upd = tr._updaters[0]
        for i in self._tr_idx:
            upd._ensure_state(i, params[i].data())
        self._setup_done = True

    def _eligibility(self) -> Optional[str]:
        """None when the compiled path may run, else the fallback
        reason.  Cheap (host-only), re-checked every step so e.g. a
        kvstore initialized later is still honored."""
        tr = self.trainer
        if not tr._kv_initialized:
            tr._init_kvstore()
        if tr._update_on_kvstore:
            return ("update_on_kvstore=True: server-side updates see "
                    "one gradient at a time")
        if tr._kvstore is not None and tr._kvstore.is_distributed:
            return ("distributed kvstore: gradient exchange happens "
                    "outside the step program")
        if tr._compression_params is not None:
            return "gradient compression configured on the kvstore"
        if len(tr._contexts) != 1:
            return (f"{len(tr._contexts)} device contexts (compiled "
                    "step is single-context; use parallel."
                    "DataParallelTrainer for SPMD)")
        if any(p.grad_req == "add" for p in tr._params):
            return ("grad_req='add': gradient accumulation across "
                    "backwards has no one-step equivalent")
        if not self._tr_idx:
            return "no trainable parameters"
        from .. import envs
        if not envs.get("MXTPU_FUSED_UPDATE"):
            return ("MXTPU_FUSED_UPDATE=0 disables the fused optimizer "
                    "program the compiled step splices in")
        if not self._zero_noted and envs.get("MXTPU_ZERO_STAGE"):
            # not a fallback — the compiled path still runs, the env
            # var just cannot apply here (no dp axis on a single
            # context); say so once instead of silently ignoring it
            self._zero_noted = True
            from .. import telemetry
            telemetry.record_event(
                "zero_inapplicable", name=self.name,
                stage=int(envs.get("MXTPU_ZERO_STAGE")),
                reason="CompiledStep is single-context; the ZeRO "
                       "sharded update needs the SPMD "
                       "DataParallelTrainer's dp mesh axis "
                       "(docs/zero.md)")
        if not self._integrity_noted:
            from ..elastic import faults as _faults
            if _faults._active and any(
                    s.point in _faults.CORRUPT_POINTS
                    for s in _faults._specs):
                # a corruption drill armed where no cross-replica
                # detector exists (single context = one replica —
                # nothing to disagree with): the drill would "fire"
                # while proving nothing, so say so once, loudly
                self._integrity_noted = True
                from .. import telemetry
                telemetry.record_event(
                    "integrity_inapplicable", name=self.name,
                    reason="CompiledStep is single-context; the "
                           "corrupt_* drills need the SPMD "
                           "DataParallelTrainer's >1-device dp axis "
                           "for the cross-replica agreement audit "
                           "(docs/elasticity.md, 'Integrity sentry')")
        # optimizer-capability checks (fused plan / tensor support) run
        # in _check_sig, which builds the plan ONCE per dispatch anyway
        return None

    # -- eager path --------------------------------------------------------
    def _eager(self, args, label, batch_size, k_steps=None, repeat=False):
        from .. import autograd
        from .. import ndarray as nd
        self.last_path = "eager"

        def one(a, l):
            with autograd.record():
                out = self.net(*a)
                loss = self.loss_fn(out, l)
            autograd.backward([loss])
            self.trainer.step(batch_size)
            return loss

        if k_steps is None:
            return one(args, label)
        losses = []
        for k in range(k_steps):
            a = args if repeat else [x[k] for x in args]
            l = label if repeat else label[k]
            losses.append(one(a, l))
        return nd.stack(*losses)

    # -- compiled path -----------------------------------------------------
    def _state_leaves(self) -> List[NDArray]:
        """Fresh each step: ``load_states`` swaps the NDArray objects,
        so cached leaves would silently update dead buffers."""
        upd = self.trainer._updaters[0]
        leaves: List[NDArray] = []
        for i in self._tr_idx:
            _flatten_state(upd.states[i], leaves)
        return leaves

    def _check_sig(self, n_state, n_args):
        """Build this step's plan (the optimizer's static surface) and
        evict stale executables when it drifted (momentum/beta/clip/...
        changes are baked into the trace — correctness over cache
        warmth).  Also the capability gate: raises ``_TraceFallback``
        (caught upstream → transparent eager) when the optimizer has no
        fused program or the tensors are unsupported."""
        from .. import engine
        tr = self.trainer
        opt = tr._optimizer
        weights = [self._params[i].data() for i in self._tr_idx]
        upd = tr._updaters[0]
        if not opt._fused_supported(weights, weights):
            raise _TraceFallback(
                "optimizer tensors unsupported by the fused path "
                "(sparse grads or mixed precision set)")
        plan = opt._fused_plan(list(self._tr_idx), weights, weights,
                               [upd.states[i] for i in self._tr_idx])
        if plan is None:
            raise _TraceFallback(
                f"optimizer {type(opt).__name__} has no fused "
                "multi-tensor program (_fused_plan returned None)")
        # the health plane's layout + skip gate are baked into the
        # traced program (extra outputs), so they belong to the sig:
        # flipping MXTPU_HEALTH* evicts + retraces ONCE, attributed
        from .. import telemetry
        hspec = telemetry.health.build_spec(
            self.net.name,
            [self._params[i].name for i in self._tr_idx])
        hsig = hspec.signature() if hspec is not None else None
        sig = (plan.op_name, tuple(sorted(plan.attrs.items())),
               n_state, n_args, hsig)
        if self._sig is not None and sig != self._sig:
            # retrace-cause attribution: the optimizer's static surface
            # drifted (momentum/beta/clip change) — name the exact
            # attrs, old -> new, before evicting the stale executable.
            # The engine cannot see this (the step's cache key carries
            # no attrs; the drift lives in the traced closure).
            from .. import telemetry
            if telemetry.enabled():
                changed = engine._sig_diff(self._sig[1], sig[1])
                if self._sig[0] != sig[0]:
                    changed["op_name"] = [self._sig[0], sig[0]]
                if self._sig[2:4] != sig[2:4]:
                    changed["structure"] = [list(self._sig[2:4]),
                                            list(sig[2:4])]
                if self._sig[4] != sig[4]:
                    def _hlabel(h):
                        if h is None:
                            return "off"
                        return "on(skip-gate)" if h[2] else "on"
                    changed["health"] = [_hlabel(self._sig[4]),
                                         _hlabel(sig[4])]
                telemetry.counter(
                    "mxtpu_retraces_total",
                    "cache misses attributable to a changed "
                    "attr/shape/dtype").inc()
                telemetry.record_event(
                    "retrace", op=self.name, cause="attrs",
                    changed=changed, source="compiled_step")
            for name in self._active_names:
                engine.drop_cached(name)
            self._core = None
            self._core_shape = None
            # the recorded manifest rows describe the PRE-drift
            # programs (output arity included) — a save_signature
            # after the drift must re-record, or a warm start would
            # compile a variant whose unpack contradicts the config
            self._variants.clear()
            # a pinned warm-start identity described the PRE-drift
            # program; re-derive so the persistent tier cannot serve a
            # stale-attr executable (the attrs live in the hash)
            self._persist_pinned = False
        self._sig = sig
        self._health_spec = hspec
        import hashlib
        self._struct_hash = hashlib.sha256(repr(
            (sig, tuple((tuple(p.data().shape), str(p.data().dtype))
                        for p in self._params))).encode()
            ).hexdigest()[:16]
        if not self._persist_pinned:
            self._persist_base = \
                f"gluon_step_{self.net.name}_{self._struct_hash}"

    def _dispatch(self, args, label, batch_size, k_steps=None,
                  repeat=False):
        import jax
        import jax.numpy as jnp
        from .. import engine
        from .. import random as _rnd
        tr = self.trainer
        opt = tr._optimizer
        ctx = args[0].context
        params = self._params
        tr_idx = self._tr_idx
        n_args = len(args)

        opt.rescale_grad = tr._scale / batch_size
        opt._set_current_context(0)
        leaf_nds = self._state_leaves()
        P, S = len(params), len(leaf_nds)
        self._check_sig(S, n_args)

        from ..elastic import faults as _faults
        if _faults._active and _faults.nonfinite_due(self.name):
            # the nonfinite drill: a NaN planted in the batch reaches
            # the loss/gradients through the UNCHANGED compiled program
            # (same shapes — no retrace, no extra dispatch).  AFTER
            # _check_sig: its _TraceFallback (-> eager replay with the
            # ORIGINAL args) must not consume the one-shot spec and
            # report a drill that never happened
            from .. import telemetry as _tm
            args = _tm.health.poison_inputs(args, ctx)

        # host bookkeeping snapshot: a pre-dispatch (trace/compile)
        # failure must rewind counts and the RNG stream so the eager
        # fallback replays the step identically
        count_snap = (dict(opt._index_update_count), opt.num_update)
        key_snap = dict(_rnd._keys)
        idx = list(tr_idx)
        if k_steps is None:
            opt._update_count(idx)
            scal_rows = [opt.fused_step_scalars(idx)]
            keys = [_rnd._next_key_nd(ctx)._data]
        else:
            scal_rows = []
            keys = []
            for _ in range(k_steps):
                opt._update_count(idx)
                scal_rows.append(opt.fused_step_scalars(idx))
                keys.append(_rnd._next_key_nd(ctx)._data)
        C = len(scal_rows[0])
        if k_steps is None:
            scal_vals = list(scal_rows[0])
            key_vals = [keys[0]]
        else:
            scal_vals = [np.stack([np.asarray(r[c]) for r in scal_rows])
                         for c in range(C)]
            key_vals = [jnp.stack(keys)]

        # health-plane variant selection (docs/observability.md): a
        # SAMPLED dispatch runs the "_hs" program variant that also
        # returns the in-graph stats vector; un-sampled steps run a
        # program byte-identical to a health-off build (a dynamic
        # branch would force the gradient tensors to materialize as
        # cond operands EVERY step — measured as a multi-%% fusion
        # barrier).  The skip gate reads the stats every step, so
        # skip mode bakes them into the base variant instead.
        hs = self._health_spec
        k_real = 1 if k_steps is None else k_steps
        sampled = False
        if hs is not None:
            from .. import telemetry as _tm
            sampled = bool(_tm.health.due_flags(
                self._health_count, k_real).any())
        health_on = hs is not None and (hs.skip or sampled)
        hsuffix = "_hs" if (health_on and not hs.skip) else ""
        # a bulked sampled variant carries per-inner-step due flags so
        # only boundary steps pay the stat reductions (a K>=EVERY bulk
        # selects _hs on every dispatch)
        with_due = bool(hsuffix) and k_steps is not None

        core = self._get_core(P, S, C, n_args, ctx, health_on)
        if k_steps is None:
            pure = self._make_pure(core, P, S, C)
            suffix = hsuffix
            # donate trainable weights + ALL optimizer state leaves;
            # frozen params and the (autograd-owned) inputs are not ours
            # to alias
            donate = tuple(tr_idx) + tuple(range(P, P + S))
        else:
            pure = self._make_pure_k(core, P, S, C, n_args, k_steps,
                                     repeat, health_on=health_on,
                                     with_due=with_due)
            suffix = f"_k{k_steps}" + ("r" if repeat else "") + hsuffix
            # the scan carries (and returns) EVERY param, so all of
            # them may donate
            donate = tuple(range(P + S))
        name = self.name + suffix
        if suffix:
            self._active_names.add(name)
        persist_name = self._persist_base + suffix

        flat = [p.data()._data for p in params] \
            + [s._data for s in leaf_nds] + scal_vals \
            + [a._data for a in args] + [label._data] + key_vals
        if with_due:
            from .. import telemetry as _tm
            flat.append(jnp.asarray(_tm.health.due_flags(
                self._health_count, k_steps)))
        try:
            if not self._trace_seen[0] and engine.persist.enabled() \
                    and engine.persist.contains(
                        persist_name, (), donate,
                        engine.persist.aval_sig(flat)):
                # a persistent-tier hit skips the Python trace, and
                # with it the mutated_idx discovery (the BatchNorm-aux
                # write-back routing).  One abstract trace recovers it
                # — host-only, no compile.  Trace failures land in the
                # except below exactly like a jit-path trace failure.
                jax.eval_shape(pure, *flat)
            res = engine.invoke_compiled(name, pure, {}, *flat,
                                         donate=donate,
                                         persist_name=persist_name)
        except Exception as e:
            consumed = any(getattr(v, "is_deleted", lambda: False)()
                           for v in flat)
            if consumed:
                # post-donation failure: the old buffers are gone and
                # no new ones exist — training state is unrecoverable
                # (same protocol as the fused optimizer / SPMD trainer)
                self._poisoned = repr(e)
                from .. import telemetry
                telemetry.counter(
                    "mxtpu_poisons_total",
                    "post-donation failures (training state lost)"
                    ).inc()
                telemetry.record_event(
                    "poison", where="compiled_step", name=self.name,
                    error=repr(e)[:500])
                telemetry.auto_dump(
                    reason=f"compiled_step_poisoned:{self.name}")
                raise MXNetError(
                    "compiled train step failed AFTER its weight/state "
                    "buffers were donated; call recover(manager) to "
                    "restore from the last committed checkpoint "
                    "(docs/elasticity.md). Original error: "
                    f"{e!r}") from e
            # pre-dispatch failure (trace/compile): rewind host state
            # and let the caller fall back to eager transparently
            opt._index_update_count.clear()
            opt._index_update_count.update(count_snap[0])
            opt.num_update = count_snap[1]
            _rnd._keys.clear()
            _rnd._keys.update(key_snap)
            raise _TraceFallback(
                f"whole-step trace/compile failed: {e!r}") from e

        self.last_path = "compiled"
        # warm-start manifest row: everything a fresh process needs to
        # precompile this exact variant before its first batch — built
        # once per variant, not per step (the aval walk over a
        # BERT-sized flat list is not free)
        self._dims = (P, S, C, n_args)
        vkey = (k_steps or 0, bool(repeat), health_on)
        if vkey not in self._variants:
            self._variants[vkey] = {
                "suffix": suffix, "k_steps": k_steps,
                "repeat": bool(repeat), "health_out": health_on,
                "donate": [int(i) for i in donate],
                "avals": engine.persist.sig_to_json(
                    engine.persist.aval_sig(flat))}
        T = len(tr_idx)
        health_out = None
        if health_on:
            health_out, res = res[-1], res[:-1]
        if k_steps is None:
            loss_val = res[0]
            new_tr = res[1:1 + T]
            new_leaves = res[1 + T:1 + T + S]
            aux = res[1 + T + S:]
            for i, v in zip(self._mutated_idx, aux):
                params[i].data()._set_data(v)
            for j, i in enumerate(tr_idx):
                params[i].data()._set_data(new_tr[j])
        else:
            loss_val = res[0]
            new_all = res[1:1 + P]
            new_leaves = res[1 + P:1 + P + S]
            for p, v in zip(params, new_all):
                p.data()._set_data(v)
        for s, v in zip(leaf_nds, new_leaves):
            s._set_data(v)
        if health_on:
            from .. import telemetry as _tm
            _tm.health.sample_owner(
                self, self.name, hs, health_out, k_real)
        elif hs is not None:
            # un-sampled variant: keep the cadence counter moving so
            # the next sampled step lands on the K boundary
            self._health_count += k_real
        return NDArray(loss_val, ctx=ctx)

    # -- traced functions --------------------------------------------------
    def _get_core(self, n_params, n_state, n_scal, n_args, ctx,
                  health_on=False):
        """The pure step body shared by ``step`` and ``step_multi``:
        (params, state_leaves, scalars, inputs, label, key) ->
        (loss, new_trainable, new_state_leaves, aux, health).

        ``health_on`` bakes the health-plane stats into THIS variant
        of the program (docs/observability.md): sampling is variant
        SELECTION, not a dynamic branch — a conditional would force
        XLA to materialize the gradient tensors (cond operands) on
        every step, a measured fusion barrier, whereas the un-sampled
        variant here stays byte-identical to a health-off build."""
        if self._core is not None and \
                self._core_shape == (n_params, n_state, n_scal, n_args,
                                     health_on):
            return self._core
        net, loss_fn, tr = self.net, self.loss_fn, self.trainer
        params = self._params
        tr_idx = list(self._tr_idx)
        tr_set = set(tr_idx)
        mutated_idx = self._mutated_idx
        trace_seen = self._trace_seen
        hspec = self._health_spec if health_on else None

        def core(param_vals, state_vals, scal_vals, input_vals,
                 label_val, key_raw, due=None):
            import jax
            trace_seen[0] = True     # body runs only under a trace
            import jax.numpy as jnp
            from .. import autograd
            from .. import random as _rnd
            from ..ops.registry import get_op
            opt = tr._optimizer
            upd = tr._updaters[0]
            reps = [p.data() for p in params]
            key_counter = [0]

            def key_provider(_ctx):
                k = jax.random.fold_in(
                    jax.random.wrap_key_data(key_raw), key_counter[0])
                key_counter[0] += 1
                return NDArray(jax.random.key_data(k), ctx=ctx)

            _rnd._push_key_provider(key_provider)
            prev = autograd.set_training(True)
            try:
                with block_mod.tracing_scope(reps):
                    def loss_of(tvals):
                        vers = []
                        for j, i in enumerate(tr_idx):
                            reps[i]._buf = tvals[j]
                        for i, r in enumerate(reps):
                            if i not in tr_set:
                                r._buf = param_vals[i]
                            vers.append(r._version)
                        shells = [NDArray(v, ctx=ctx)
                                  for v in input_vals]
                        out = net(*shells)
                        l = loss_fn(out, NDArray(label_val, ctx=ctx))
                        if not isinstance(l, NDArray):
                            raise MXNetError(
                                "CompiledStep loss_fn must return a "
                                f"single NDArray, got {type(l)}")
                        mutated_idx.clear()
                        mutated_idx.extend(
                            i for i, (r, v0) in enumerate(
                                zip(reps, vers))
                            if r._version != v0)
                        aux = tuple(reps[i]._buf for i in mutated_idx)
                        # grads of the SUM = the ones-cotangent
                        # loss.backward() applies to an unreduced loss
                        return jnp.sum(l._data), (l._data, aux)

                    tvals = tuple(param_vals[i] for i in tr_idx)
                    (_, (loss_val, aux)), grads = jax.value_and_grad(
                        loss_of, has_aux=True)(tvals)

                    # optimizer splice: the SAME multi-tensor program
                    # fused_update dispatches, with traced tensors and
                    # the per-step scalars as runtime inputs
                    w_shells = [NDArray(v, ctx=ctx) for v in tvals]
                    g_shells = [NDArray(g, ctx=ctx) for g in grads]
                    leaf_shells = [NDArray(v, ctx=ctx)
                                   for v in state_vals]
                    it = iter(leaf_shells)
                    shadow = [_rebuild_state(upd.states[i], it)
                              for i in tr_idx]
                    plan = opt._fused_plan(tr_idx, w_shells, g_shells,
                                           shadow)
                    res = get_op(plan.op_name).fcompute(
                        *[x._data for x in plan.inputs], *scal_vals,
                        **plan.attrs)
                    if not isinstance(res, tuple):
                        res = (res,)
                    w_pos = {id(x): j for j, x in enumerate(w_shells)}
                    s_pos = {id(x): j
                             for j, x in enumerate(leaf_shells)}
                    new_tr = list(tvals)
                    new_leaves = list(state_vals)
                    for k, o in enumerate(plan.outs):
                        if id(o) in w_pos:
                            new_tr[w_pos[id(o)]] = res[k]
                        elif id(o) in s_pos:
                            new_leaves[s_pos[id(o)]] = res[k]
                    health_vec = None
                    if hspec is not None:
                        from .. import telemetry as _tm
                        # `due` is None except in the bulked sampled
                        # variant, where per-inner-step flags gate the
                        # reductions (a K>=EVERY bulk would otherwise
                        # pay the stats on every inner step)
                        health_vec = _tm.health.compute(
                            hspec, loss_val, tvals, grads,
                            tuple(new_tr), due=due)
                        if hspec.skip:
                            # in-graph skip: a nonfinite step writes
                            # the PRE-step values back out — the old
                            # values are still readable here even
                            # though the buffers are donated (aliasing
                            # is the compiler's problem, not ours)
                            _gate = _tm.health.gate
                            new_tr = list(_gate(health_vec, new_tr,
                                                tvals))
                            new_leaves = list(_gate(
                                health_vec, new_leaves, state_vals))
                            aux = _gate(
                                health_vec, aux,
                                tuple(param_vals[i]
                                      for i in mutated_idx))
            finally:
                autograd.set_training(prev)
                _rnd._pop_key_provider()
            return (loss_val, tuple(new_tr), tuple(new_leaves), aux,
                    health_vec)

        self._core = core
        self._core_shape = (n_params, n_state, n_scal, n_args,
                            health_on)
        return core

    def _make_pure(self, core, P, S, C):
        def pure(*flat):
            param_vals = flat[:P]
            state_vals = flat[P:P + S]
            scal_vals = flat[P + S:P + S + C]
            input_vals = flat[P + S + C:-2]
            label_val, key_raw = flat[-2], flat[-1]
            loss_val, new_tr, new_leaves, aux, health_vec = core(
                param_vals, state_vals, scal_vals, input_vals,
                label_val, key_raw)
            out = (loss_val,) + new_tr + new_leaves + aux
            # the health vector rides as the LAST output so the aux
            # slice stays positional (its length is only known after
            # the trace populated mutated_idx)
            if health_vec is not None:
                out = out + (health_vec,)
            return out
        return pure

    def _make_pure_k(self, core, P, S, C, n_args, k_steps, repeat,
                     health_on=False, with_due=False):
        tr_idx = list(self._tr_idx)
        mutated_idx = self._mutated_idx

        def pure_k(*flat):
            from jax import lax
            param_vals = tuple(flat[:P])
            state_vals = tuple(flat[P:P + S])
            scal_k = tuple(flat[P + S:P + S + C])   # each (K, ...)
            rest = flat[P + S + C:]
            input_vals = tuple(rest[:n_args])
            label_val = rest[n_args]
            keys_k = rest[n_args + 1]
            due_k = rest[n_args + 2] if with_due else None

            def body(carry, xs):
                pv, sv = carry
                due = None
                if with_due:
                    *xs, due = xs
                if repeat:
                    scal, key = xs
                    iv, lv = input_vals, label_val
                else:
                    scal, iv, lv, key = xs
                loss_val, new_tr, new_leaves, aux, health_vec = core(
                    pv, sv, scal, iv, lv, key, due)
                pv = list(pv)
                # forward-mutated (aux) params join the carry so step
                # k+1 sees step k's BatchNorm running stats; trainable
                # writes go LAST so a param that is both mutated and
                # trainable ends on the optimizer's value — the same
                # precedence step()'s write-back applies
                for j, i in enumerate(mutated_idx):
                    pv[i] = aux[j]
                for j, i in enumerate(tr_idx):
                    pv[i] = new_tr[j]
                ys = loss_val if health_vec is None else \
                    (loss_val, health_vec)
                return (tuple(pv), new_leaves), ys

            xs = (scal_k, keys_k) if repeat else \
                (scal_k, input_vals, label_val, keys_k)
            if with_due:
                xs = xs + (due_k,)
            (pf, sf), ys = lax.scan(
                body, (param_vals, state_vals), xs)
            if health_on:
                losses, healths = ys       # healths: (K, n_slots)
                return (losses,) + pf + sf + (healths,)
            return (ys,) + pf + sf
        return pure_k


class _TraceFallback(MXNetError):
    """Internal: compiled-path failure that the eager path can absorb."""
