"""``mx.gluon``: imperative/hybridizable neural-network API.

Capability parity: reference ``python/mxnet/gluon/`` — SURVEY.md §2.5.
"""
from .parameter import (Parameter, ParameterDict, Constant,
                        DeferredInitializationError)
from .block import Block, HybridBlock, SymbolBlock, CachedOp
from .trainer import Trainer
from .compiled_step import CompiledStep
from . import nn
from . import loss
from . import utils
from . import data
from . import rnn
from . import model_zoo

__all__ = ["Parameter", "ParameterDict", "Constant", "Block", "HybridBlock",
           "SymbolBlock", "CachedOp", "Trainer", "CompiledStep", "nn",
           "loss", "utils", "data", "DeferredInitializationError"]
