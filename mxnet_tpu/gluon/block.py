"""Gluon Block / HybridBlock and the CachedOp (hybridize → XLA seam).

Capability parity: reference ``python/mxnet/gluon/block.py`` +
``src/imperative/cached_op.cc`` (SURVEY.md §2.1, §2.5, call stack §3.3).

TPU-native design — THE seam (SURVEY.md §3.3): ``hybridize()`` does not
build an nnvm graph; instead ``CachedOp`` traces the block's imperative
forward (pure JAX ops under the hood) into one jitted executable, cached per
(input shapes, dtypes, train-mode) exactly like the reference caches
GraphInfo per (shape, dtype, ctx).  XLA then owns memory planning, fusion
and layout — the jobs nnvm's PlanMemory/bulking did.

Mechanics worth knowing:
* Parameter/aux mutation inside the graph (BatchNorm moving stats) is
  functionalized: the trace detects buffer-version bumps and returns the new
  values as extra outputs, which ``CachedOp.__call__`` writes back after the
  compiled call — reproducing the reference's aux-array update semantics.
* RNG (Dropout) is threaded as a *base key input* + per-request ``fold_in``,
  so each compiled call uses fresh masks without recompiling.
* Under ``autograd.record()`` the whole cached op joins the tape as ONE node
  via ``jax.vjp`` over the jitted function (compiled forward AND backward) —
  the analog of ``CachedOp::Backward``'s cached gradient graph.
"""
from __future__ import annotations

import contextlib
import copy
import re
import threading
from collections import OrderedDict
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from .parameter import (Parameter, ParameterDict, Constant,
                        DeferredInitializationError)

__all__ = ["Block", "HybridBlock", "SymbolBlock", "CachedOp"]

_naming = threading.local()


class _BlockScope:
    """Name manager: gives blocks unique prefixes (parity: _BlockScope)."""

    _counters = {}

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_naming, "current", None)
        if current is None:
            if prefix is None:
                count = _BlockScope._counters.setdefault(hint, 0)
                prefix = f"{hint}{count}_"
                _BlockScope._counters[hint] += 1
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.setdefault(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] += 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_naming, "current", None)
        _naming.current = self
        return self

    def __exit__(self, *exc):
        if self._block._empty_prefix:
            return
        _naming.current = self._old_scope


class Block:
    """Base class for all neural-network layers and models."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()

    def _alias(self):
        return self.__class__.__name__.lower()

    # -- attribute magic: auto-register children & params -----------------
    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)) and \
                    not isinstance(existing, type(value)):
                raise TypeError(
                    f"Changing attribute type for {self.name!r} from "
                    f"{type(existing)} to {type(value)} is not allowed.")
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute is not allowed. " \
                "If you want to share parameters between blocks, please " \
                "pass `params` at construction."
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            f"  ({key}): {_indent(repr(block), 2)}"
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr) \
            if modstr else f"{self.__class__.__name__}()"

    # -- identity ----------------------------------------------------------
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self) -> ParameterDict:
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        """All Parameters of this block and children (regex filterable)."""
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        handle = _HookHandle(self._forward_hooks)
        self._forward_hooks[handle._id] = hook
        return handle

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle._id] = hook
        return handle

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # -- lifecycle ---------------------------------------------------------
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def zero_grad(self):
        self.collect_params().zero_grad()

    def reset_ctx(self, ctx):
        self.collect_params().reset_ctx(ctx)

    # -- (de)serialization -------------------------------------------------
    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename, deduplicate=False):
        """Save params keyed by attribute path (robust to prefix changes)."""
        params = self._collect_params_with_prefix()
        arg_dict = {}
        seen = {}
        for key, param in params.items():
            if deduplicate and id(param) in seen:
                continue
            seen[id(param)] = key
            arg_dict[key] = param._check_and_get(param._data, None)
        nd.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        loaded = nd.load(filename)
        if not isinstance(loaded, dict):
            raise MXNetError(
                f"file {filename!r} holds an unnamed NDArray list, not "
                "named parameters")
        # reference Module checkpoints prefix keys with arg:/aux: —
        # upstream load_parameters strips these, so we must too
        loaded = {(k[4:] if k.startswith(("arg:", "aux:")) else k): v
                  for k, v in loaded.items()}
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        # legacy fallback: file saved with FULL param names (reference
        # Module checkpoints, nd.save of collect_params()) — detect by
        # a key that resolves as a param name but not as an attribute
        # path, or by the dotted-path shape heuristic
        by_name = {p.name: p for p in self.collect_params().values()}
        if (any(k in by_name and k not in params for k in loaded)
                or (not any("." in k for k in loaded.keys())
                    and any("." in k for k in params.keys()))):
            for name, value in loaded.items():
                if name in by_name:
                    by_name[name]._load_init(value, ctx,
                                             cast_dtype=cast_dtype)
                elif not ignore_extra:
                    raise MXNetError(
                        f"Parameter {name!r} loaded from file {filename!r} "
                        "is not present in this Block")
            return
        if not allow_missing:
            for name in params.keys():
                if name not in loaded:
                    raise MXNetError(
                        f"Parameter {name!r} is missing in file "
                        f"{filename!r}, which contains parameters: "
                        f"{_brief_print_list(loaded.keys())}")
        for name in loaded:
            if name not in params:
                if not ignore_extra:
                    raise MXNetError(
                        f"Parameter {name!r} loaded from file {filename!r} "
                        "is not present in this Block")
                continue
            params[name]._load_init(loaded[name], ctx, cast_dtype=cast_dtype)

    # -- call path ---------------------------------------------------------
    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        """Recursively activate hybridization on HybridBlock children."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        """Print a per-layer summary given sample inputs."""
        summary = OrderedDict()
        hooks = []

        def _register(block):
            def _hook(blk, _, outputs):
                cname = blk.__class__.__name__
                key = f"{cname}-{len(summary) + 1}"
                outs = outputs if isinstance(outputs, (list, tuple)) \
                    else [outputs]
                summary[key] = (tuple(getattr(o, "shape", ()) for o in outs),
                                sum(int(np.prod(p.shape))
                                    for p in blk._reg_params.values()
                                    if p.shape))
            hooks.append(block.register_forward_hook(_hook))

        self.apply(_register)
        try:
            self(*inputs)
            print(f"{'Layer':<30}{'Output Shape':<30}{'Params':<15}")
            print("-" * 75)
            total = 0
            for key, (shapes, nparams) in summary.items():
                print(f"{key:<30}{str(shapes):<30}{nparams:<15}")
                total += nparams
            print("-" * 75)
            print(f"Total params: {total}")
        finally:
            for h in hooks:
                h.detach()


class _HookHandle:
    _counter = [0]

    def __init__(self, hooks_dict):
        self._hooks_dict = hooks_dict
        _HookHandle._counter[0] += 1
        self._id = _HookHandle._counter[0]

    def detach(self):
        self._hooks_dict.pop(self._id, None)


def _indent(s, num):
    lines = s.split("\n")
    return ("\n" + " " * num).join(lines)


def _brief_print_list(lst, limit=7):
    lst = list(lst)
    if len(lst) > limit:
        return ", ".join(map(repr, lst[:limit // 2])) + ", ..., " + \
            ", ".join(map(repr, lst[-limit // 2:]))
    return ", ".join(map(repr, lst))


# ---------------------------------------------------------------------------
# CachedOp
# ---------------------------------------------------------------------------

_trace_state = threading.local()


def _is_tracing() -> bool:
    return getattr(_trace_state, "active", False)


@contextlib.contextmanager
def tracing_scope(param_nds=(), param_vals=None):
    """Enter the trace seam: NDArray ops apply directly on jax tracers
    instead of dispatching compiled programs.

    Optionally swaps each NDArray in ``param_nds`` to the traced value
    at the same position of ``param_vals``; buffers AND versions are
    restored on exit, so in-place mutation during the trace cannot
    leak into the imperative state.  Yields the saved
    ``[(buf, version), ...]`` list so callers can detect in-trace
    mutation (version drift).  Used by CachedOp's ``pure()``, the
    fused trainer, ``deploy._functionalize``, and fused generation
    loops — the save/restore choreography lives in ONE place.
    """
    saved = [(r._buf, r._version) for r in param_nds]
    prev = getattr(_trace_state, "active", False)
    _trace_state.active = True
    try:
        if param_vals is not None:
            for r, v in zip(param_nds, param_vals):
                r._buf = v
        yield saved
    finally:
        _trace_state.active = prev
        for r, (buf, ver) in zip(param_nds, saved):
            r._buf = buf
            r._version = ver


class _CacheEntry:
    __slots__ = ("jitted", "n_real_out", "mutated_idx", "out_tree",
                 "out_avals")

    def __init__(self):
        self.jitted = None
        self.n_real_out = 0
        self.mutated_idx = ()
        self.out_tree = None
        self.out_avals = None


def _flatten_args(args):
    """Flatten nested (list/tuple of) NDArray args into leaves + treedef
    (cells pass state lists; attention passes mask tuples).  numpy arrays
    become NDArray leaves (data, not compile-time constants); other
    non-array values are static and keyed by repr — like jit static args,
    a changing static value recompiles."""
    import numpy as _np
    from .. import ndarray as _nd
    leaves = []

    def go(x):
        if isinstance(x, _np.ndarray):
            x = _nd.array(x, dtype=x.dtype)
        if isinstance(x, NDArray):
            leaves.append(x)
            return ("L", len(leaves) - 1)
        if isinstance(x, (list, tuple)):
            return ("l" if isinstance(x, list) else "t",
                    tuple(go(y) for y in x))
        return ("C", x)  # static constant (None, scalars, strings)

    tree = tuple(go(a) for a in args)
    return leaves, tree


def _tree_cache_key(tree):
    """Hashable form of a treedef (constants may be unhashable)."""

    def go(t):
        tag = t[0]
        if tag in ("l", "t"):
            return (tag, tuple(go(y) for y in t[1]))
        if tag == "C":
            try:
                hash(t[1])
                return ("C", t[1])
            except TypeError:
                return ("C", repr(t[1]))
        return t

    return tuple(go(t) for t in tree)


def jax_tree_leaves_of_ndarrays(out):
    """Raw jax buffers of every NDArray in a (possibly nested) result —
    what block_until_ready understands."""
    bufs = []

    def go(x):
        if isinstance(x, NDArray):
            bufs.append(x._data)
        elif isinstance(x, (list, tuple)):
            for y in x:
                go(y)

    go(out)
    return bufs


def _unflatten_args(tree, leaves):
    def go(t):
        tag = t[0]
        if tag == "L":
            return leaves[t[1]]
        if tag == "C":
            return t[1]
        seq = [go(y) for y in t[1]]
        return seq if tag == "l" else tuple(seq)

    return [go(t) for t in tree]


class CachedOp:
    """Compiled-executable cache for a HybridBlock (parity: CachedOp)."""

    _uid = [0]

    def __init__(self, block: "HybridBlock", static_alloc=False,
                 static_shape=False):
        self.block = block
        self.static_alloc = static_alloc      # accepted for API parity;
        self.static_shape = static_shape      # XLA always plans statically
        self._entries = {}
        self._param_list: Optional[List[Parameter]] = None
        CachedOp._uid[0] += 1
        self.name = f"cachedop_{block.name}_{CachedOp._uid[0]}"

    def _collect_param_arrays(self, leaves, call_args):
        """Stable ordered list of param NDArray replicas for the call ctx."""
        if self._param_list is None:
            params = list(self.block.collect_params().values())
            if any(p._deferred_init for p in params):
                # one imperative warm-up run resolves every deferred shape
                from .. import autograd
                with autograd.pause():
                    self.block._call_unhybridized(*call_args)
            self._param_list = params
        ctx = leaves[0].context if leaves else None
        out = []
        for p in self._param_list:
            d = p._check_and_get(p._data, None)
            if ctx is not None and ctx != d.context:
                d = p.data(ctx)
            out.append(d)
        return out

    def _get_entry(self, param_nds, leaves, tree, ctx,
                   training) -> _CacheEntry:
        key = (tuple((a.shape, a.dtype.name) for a in leaves),
               _tree_cache_key(tree),
               tuple((p.shape, p.dtype.name) for p in param_nds),
               training, ctx)
        entry = self._entries.get(key)
        if entry is not None:
            return entry
        import jax
        entry = _CacheEntry()
        block = self.block
        params = self._param_list
        n_params = len(param_nds)
        n_args = len(leaves)

        def pure(*flat):
            """Functionalized forward: (params…, inputs…, base_key) →
            (outputs…, mutated-param-values…)."""
            from .. import random as _rnd
            # resolve the per-context replica NDArrays at trace time from
            # the Parameter objects, so the closure never pins stale
            # buffers across load_parameters/reset_ctx
            reps = [p.data(ctx) for p in params]
            param_vals = flat[:n_params]
            input_vals = flat[n_params:n_params + n_args]
            base_key_raw = flat[-1]
            key_counter = [0]

            def key_provider(_ctx):
                k = jax.random.fold_in(
                    jax.random.wrap_key_data(base_key_raw), key_counter[0])
                key_counter[0] += 1
                return NDArray(jax.random.key_data(k), ctx=ctx)

            _rnd._push_key_provider(key_provider)
            try:
                with tracing_scope(reps, param_vals) as saved:
                    shells = [NDArray(v, ctx=ctx) for v in input_vals]
                    call_args = _unflatten_args(tree, shells)
                    outs = block._call_unhybridized(*call_args)
                    # outputs may nest (RNN layers return (seq,
                    # [h, c])) — flatten with the same tree scheme as
                    # the inputs
                    out_leaves, out_tree = _flatten_args((outs,))
                    out_data = tuple(o._data for o in out_leaves)
                    mutated_idx = tuple(
                        i for i, (r, s) in enumerate(zip(reps, saved))
                        if r._version != s[1])
                    mutated_vals = tuple(reps[i]._buf
                                         for i in mutated_idx)
            finally:
                _rnd._pop_key_provider()
            entry.n_real_out = len(out_data)
            entry.mutated_idx = mutated_idx
            entry.out_tree = out_tree
            return out_data + mutated_vals

        from .. import autograd

        def pure_in_mode(*flat):
            prev = autograd.set_training(training)
            try:
                return pure(*flat)
            finally:
                autograd.set_training(prev)

        entry.jitted = jax.jit(pure_in_mode)
        self._entries[key] = entry
        return entry

    def __call__(self, *args):
        from .. import profiler
        with profiler._span(f"CachedOp[{self.block.name}]",
                            "cachedop") as sp:
            out = self._execute(args)
            sp.sync(jax_tree_leaves_of_ndarrays(out))
            return out

    def _execute(self, args):
        from .. import autograd
        from .. import random as _rnd
        import jax

        leaves, tree = _flatten_args(args)
        param_nds = self._collect_param_arrays(leaves, args)
        training = autograd.is_training()
        ctx = leaves[0].context if leaves else current_context()
        entry = self._get_entry(param_nds, leaves, tree, ctx, training)
        base_key = _rnd._next_key_nd(ctx)

        flat = [p._data for p in param_nds] + [a._data for a in leaves] \
            + [base_key._data]

        try:
            if autograd.is_recording():
                out_all, vjp_fn = jax.vjp(entry.jitted, *flat)

                def vjp_tuple(cots, _fn=vjp_fn):
                    # the traced fn always returns a tuple; the tape
                    # passes a bare cotangent for a single output slot
                    return _fn(cots if isinstance(cots, tuple)
                               else (cots,))

                node = autograd._Node(
                    vjp_tuple, list(param_nds) + list(leaves), 1,
                    [o.aval for o in out_all])
            else:
                out_all = entry.jitted(*flat)
                node = None
        except jax.errors.JaxRuntimeError as e:
            # device/callback failure during execution: same error TYPE
            # whether it surfaces here (sync backend) or at the consumer
            # sync point (async backend) — the reference's
            # exception-teleporting contract is MXNetError either way
            raise MXNetError(
                f"execution error in CachedOp[{self.block.name}]: {e}"
            ) from e

        real = out_all[:entry.n_real_out]
        aux = out_all[entry.n_real_out:]
        # write mutated params back (outside the tape, like aux updates) —
        # into the per-context replicas used for this call
        for i, val in zip(entry.mutated_idx, aux):
            param_nds[i]._set_data(val)

        outs = []
        for i, o in enumerate(real):
            o_nd = NDArray(o, ctx=ctx)
            if node is not None:
                o_nd._ag_node = node
                o_nd._ag_out_idx = i
            outs.append(o_nd)
        if node is not None:
            node.outputs = list(outs)
        return _unflatten_args(entry.out_tree, outs)[0]


# ---------------------------------------------------------------------------
# HybridBlock
# ---------------------------------------------------------------------------


class HybridBlock(Block):
    """Block that can be hybridized: traced once, compiled by XLA."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op: Optional[CachedOp] = None
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        self._active = active
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape, **kwargs)
        self._cached_op = None
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def _clear_cached_op(self):
        self._cached_op = None

    def register_child(self, block, name=None):
        if not isinstance(block, HybridBlock):
            if not isinstance(block, SymbolBlock):
                # non-hybrid children make the parent fall back to
                # imperative for itself but stay callable
                pass
        super().register_child(block, name)
        if self._cached_op is not None:
            self._clear_cached_op()

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    # -- shape inference for deferred params -------------------------------
    def infer_shape(self, *args):
        """Subclasses with deferred params override to set param shapes."""
        raise MXNetError(
            f"Cannot infer shapes of deferred-initialized parameters for "
            f"{self.name!r}: layer does not implement infer_shape(). "
            "Specify in_units/in_channels explicitly.")

    def infer_type(self, *args):
        pass

    def _call_unhybridized(self, *args):
        """Run hybrid_forward imperatively, resolving deferred init."""
        ctx = args[0].context if args and isinstance(args[0], NDArray) \
            else None
        try:
            params = {k: p.data(ctx) for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer_shape(*args)
            for p in self._reg_params.values():
                p._finish_deferred_init()
            params = {k: p.data(ctx) for k, p in self._reg_params.items()}
        return self.hybrid_forward(nd, *args, **params)

    def _deferred_infer_shape(self, *args):
        self.infer_shape(*args)

    def forward(self, x, *args):
        if isinstance(x, NDArray):
            # record which positions carry arrays (None/other stays
            # literal at export time)
            self._in_sig = tuple(
                isinstance(a, NDArray) or (
                    isinstance(a, (list, tuple)) and
                    any(isinstance(e, NDArray) for e in a))
                for a in (x,) + args)
            if self._active and not _is_tracing():
                if self._cached_op is None:
                    self._cached_op = CachedOp(self, **{
                        k: v for k, v in self._flags.items()
                        if k in ("static_alloc", "static_shape")})
                return self._cached_op(x, *args)
            return self._call_unhybridized(x, *args)
        # symbolic input (Symbol tracing) — delegated to hybrid_forward
        from .. import symbol as sym_mod
        params = {k: p.var() for k, p in self._reg_params.items()}
        with _name_prefix(self.prefix):
            return self.hybrid_forward(sym_mod, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def lint(self, *input_shapes, names=None):
        """Run the mxlint graph passes (``mxnet_tpu.analysis``) over this
        block's traced graph — the same ``block(sym.var(...))`` seam
        ``export()`` serializes — without executing anything on device.

        ``input_shapes`` (optional) enables the MXL105 shape/dtype
        contract validator; ``names`` overrides the default input names
        (``data`` / ``data0..N``).  Returns the list of findings (empty
        = clean).  Imperative-only blocks (those reading ``x.shape``
        inside ``hybrid_forward``) cannot be traced and raise, exactly
        as ``export()`` would fail for them.
        """
        from .. import analysis
        from .. import symbol as sym_mod
        n = max(len(input_shapes), 1)
        names = list(names) if names else (
            ["data"] if n == 1 else [f"data{i}" for i in range(n)])
        out = self(*[sym_mod.var(nm) for nm in names])
        shapes = dict(zip(names, input_shapes)) if input_shapes else None
        return analysis.analyze_symbol(
            out, shapes=shapes, check_shapes=bool(input_shapes),
            name=self.name or type(self).__name__)

    def export(self, path, epoch=0, remove_amp_cast=True):
        """Export (parity: HybridBlock.export): writes
        ``path-symbol.json`` (the traced graph — load with
        ``SymbolBlock.imports`` or ``mx.sym.load``, no model code needed)
        and ``path-%04d.params`` (``arg:``/``aux:``-prefixed arrays, the
        reference's checkpoint layout shared with Module).
        """
        from .. import symbol as sym_mod
        sig = getattr(self, "_in_sig", None)
        if sig is None:
            raise MXNetError(
                "export() needs the input signature: run the block on "
                "real data once before exporting (parity: the reference "
                "exports the cached graph)")
        n_arrays = sum(sig)
        in_names = ["data"] if n_arrays == 1 else \
            [f"data{i}" for i in range(n_arrays)]
        it = iter(in_names)
        call_args = [sym_mod.var(next(it)) if is_arr else None
                     for is_arr in sig]
        out = self(*call_args)
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        out.save(f"{path}-symbol.json")
        aux_names = set(out.list_auxiliary_states())
        payload = {}
        for name, param in self.collect_params().items():
            arr = param._check_and_get(param._data, None)
            tag = "aux:" if name in aux_names else "arg:"
            payload[tag + name] = arr
        nd.save(f"{path}-{epoch:04d}.params", payload)


class _name_prefix:
    def __init__(self, prefix):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


class SymbolBlock(HybridBlock):
    """Block wrapping a symbolic graph (parity: gluon.SymbolBlock).

    Runs an exported model without its Python model code: the graph
    executes through a cached whole-graph Executor (one XLA program), the
    same seam ``HybridBlock.hybridize`` uses.
    """

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from .. import symbol as sym_mod
        if isinstance(outputs, (list, tuple)):
            outputs = sym_mod.Group(list(outputs))
        if isinstance(inputs, sym_mod.Symbol):
            inputs = list(inputs)
        self._sym_outputs = outputs
        self._sym_inputs = [i.name for i in inputs]
        input_set = set(self._sym_inputs)
        self._aux_names = outputs.list_auxiliary_states()
        for name in outputs.list_arguments():
            if name not in input_set:
                self.params.get(name, allow_deferred_init=True)
        for name in self._aux_names:
            self.params.get(name, grad_req="null",
                            allow_deferred_init=True)
        self._executors = {}  # (shapes, dtypes) → Executor

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """Load an exported model (parity: SymbolBlock.imports)."""
        from .. import symbol as sym_mod
        from ..context import current_context
        sym = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        block = SymbolBlock(sym, inputs)
        if param_file is not None:
            saved = nd.load(param_file)
            arg_params = {}
            for k, v in saved.items():
                name = k.split(":", 1)[1] if ":" in k else k
                arg_params[name] = v
            for name, param in block.collect_params().items():
                if name in arg_params:
                    param._load_init(arg_params[name], ctx)
                else:
                    raise MXNetError(
                        f"Parameter {name!r} missing in {param_file!r}")
        return block

    def forward(self, x, *args):
        from ..context import current_context
        inputs = [x] + list(args)
        if len(inputs) != len(self._sym_inputs):
            raise MXNetError(
                f"SymbolBlock expects {len(self._sym_inputs)} inputs "
                f"({self._sym_inputs}), got {len(inputs)}")
        key = tuple((i.shape, i.dtype.name) for i in inputs)
        executor = self._executors.get(key)
        if executor is None:
            ctx = x.context
            arg_dict = {}
            for n, i in zip(self._sym_inputs, inputs):
                arg_dict[n] = nd.zeros(i.shape, ctx=ctx,
                                       dtype=i.dtype.name)
            aux_dict = {}
            for name, p in self.collect_params().items():
                if name in self._aux_names:
                    aux_dict[name] = p.data()
                else:
                    arg_dict[name] = p.data()
            executor = self._sym_outputs.bind(
                ctx, arg_dict, grad_req="null", aux_states=aux_dict)
            self._executors[key] = executor
        kwargs = {n: i for n, i in zip(self._sym_inputs, inputs)}
        outs = executor.forward(is_train=False, **kwargs)
        return outs[0] if len(outs) == 1 else list(outs)
