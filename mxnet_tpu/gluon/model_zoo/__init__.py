"""``mx.gluon.model_zoo`` (SURVEY.md §2.6)."""
from . import vision
from .vision import get_model

__all__ = ["vision", "get_model"]
