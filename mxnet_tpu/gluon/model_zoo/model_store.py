"""Pretrained-weight store (parity: ``python/mxnet/gluon/model_zoo/
model_store.py`` — SURVEY.md §2.6 "Gluon model zoo" row).

The reference downloads ``<name>-<hash>.params`` from its model repo.
This environment has no network, so the store is a LOCAL DIRECTORY
protocol instead (documented format):

* root (default ``~/.mxnet/models``, override with ``MXNET_HOME`` or
  the ``root=`` argument) contains ``<name>.params`` files,
* a ``.params`` file is what ``Block.save_parameters`` writes (name →
  array dict), so weights trained here round-trip;
  ``get_model(..., pretrained=True)`` loads them with
  ``load_parameters``.

Drop files into the root (scp, mounted volume, …) and every zoo
constructor's ``pretrained=True`` works unchanged.
"""
from __future__ import annotations

import os

from ...base import MXNetError

__all__ = ["get_model_file", "load_pretrained"]


def _root(root=None):
    if root is not None:
        return os.path.expanduser(root)
    home = os.environ.get("MXNET_HOME")
    if home:
        return os.path.join(os.path.expanduser(home), "models")
    return os.path.expanduser(os.path.join("~", ".mxnet", "models"))


def get_model_file(name, root=None):
    """Path to ``<root>/<name>.params``; raises with instructions when
    absent (the reference would download here)."""
    root = _root(root)
    path = os.path.join(root, f"{name}.params")
    if os.path.exists(path):
        return path
    raise MXNetError(
        f"pretrained weights for {name!r} not found at {path}. This "
        "build has no network access: place a Block.save_parameters-"
        "format file there (or set MXNET_HOME / pass root=...) to use "
        "pretrained=True.")


def load_pretrained(net, name, root=None, ctx=None):
    """Initialize ``net`` from the local store; returns ``net``."""
    net.load_parameters(get_model_file(name, root), ctx=ctx)
    return net
