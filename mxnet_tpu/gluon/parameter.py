"""Gluon Parameter / ParameterDict.

Capability parity: reference ``python/mxnet/gluon/parameter.py`` (SURVEY.md
§2.5): deferred initialization (shape with 0s completed at first forward),
``grad_req`` write/add/null, per-context replicas (``list_data``), lr_mult/
wd_mult, Constant parameters, and the dict with prefix scoping + sharing.
TPU-native detail: a "context replica" is just the one device buffer —
multi-device data parallelism replicates via the sharded trainer/kvstore
path (SURVEY.md §2.3) rather than per-GPU copies.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from .. import initializer


class DeferredInitializationError(MXNetError):
    """Raised when a parameter's data is requested before shape is known."""


class Parameter:
    """A (potentially deferred-initialized) trainable tensor."""

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self._var = None
        self._data: Optional[NDArray] = None
        self._grad: Optional[NDArray] = None
        # extra per-context replicas beyond the primary (_data); keyed by
        # Context.  Single-device training never populates this — the common
        # path stays replica-free.  Multi-device DP (ctx=[...]) stores one
        # replica per context and the kvstore reduces grads across them.
        self._replicas = OrderedDict()
        self._deferred_init = ()
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        if not differentiable:
            grad_req = "null"
        self._grad_req = None
        self.grad_req = grad_req
        self._stype = stype
        self._grad_stype = grad_stype
        self._ctx = None

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self.shape}, "
                f"dtype={self.dtype})")

    # -- grad_req ----------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null"), \
            f"grad_req must be write/add/null, got {req}"
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            for arr in self._all_replicas():
                arr.grad_req = "null"
                arr._grad = None
        elif self._data is not None:
            self._init_grad()

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        assert len(self._shape) == len(new_shape) and \
            all(j in (0, i) for i, j in zip(new_shape, self._shape)), \
            f"Expected shape {new_shape} is incompatible with given shape " \
            f"{self._shape}."
        self._shape = tuple(new_shape)

    # -- initialization ----------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Initialize data & grad buffers (or defer if shape unknown)."""
        if self._data is not None and not force_reinit:
            return
        if default_init is None:
            default_init = initializer.Uniform()
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._shape is None or np.prod(self._shape) <= 0:
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise MXNetError(
                f"Cannot initialize Parameter {self.name!r} because it has "
                f"invalid shape: {self._shape}.")
        self._deferred_init = (init, ctx, default_init, None)
        self._finish_deferred_init()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        self._deferred_init = ()
        assert self._shape is not None and np.prod(self._shape) > 0, \
            f"Cannot initialize Parameter {self.name!r} because it has " \
            f"invalid shape: {self._shape}. Please specify in_units, " \
            f"in_channels, etc for `Block`s."
        if data is None:
            host = np.zeros(self._shape, dtype=self.dtype)
            chosen = init if init is not None else (
                self.init if self.init is not None else default_init)
            explicit = init is not None or self.init is not None
            chosen = initializer.create(chosen) \
                if not isinstance(chosen, initializer.Initializer) else chosen
            if explicit:
                # a per-parameter initializer bypasses name-pattern
                # dispatch (bias→0 etc.) — the user's choice wins, matching
                # the reference's InitDesc attrs['__init__'] path
                chosen._init_weight(initializer.InitDesc(self.name), host)
            else:
                chosen(initializer.InitDesc(self.name), host)
            data = nd.array(host, ctx=ctx[0], dtype=self.dtype)
        else:
            # deferred set_data payload may live on another device
            data = data.as_in_context(ctx[0])
        self._ctx = ctx[0]
        self._data = data
        self._replicas = OrderedDict()
        for c in ctx[1:]:
            self._replicas[c] = data.as_in_context(c)
        if self._grad_req != "null":
            self._init_grad()

    def _all_replicas(self):
        out = []
        if self._data is not None:
            out.append(self._data)
        out.extend(self._replicas.values())
        return out

    def _init_grad(self):
        for arr in self._all_replicas():
            arr.attach_grad(grad_req=self._grad_req,
                            stype=self._grad_stype)
        self._grad = self._data._grad

    def _load_init(self, data, ctx=None, cast_dtype=False, dtype_source=""):
        """Install loaded data (parity: Parameter._load_init)."""
        if isinstance(data, np.ndarray):
            data = nd.array(data, dtype=data.dtype)
        if self._shape is not None and builtins_any(self._shape):
            if tuple(s for s in self._shape) != data.shape and \
                    0 not in self._shape:
                raise MXNetError(
                    f"Failed loading Parameter {self.name!r} from saved "
                    f"params: shape incompatible expected {self._shape} "
                    f"vs saved {data.shape}")
        self._shape = data.shape
        if cast_dtype and np.dtype(self.dtype) != data.dtype:
            data = data.astype(self.dtype)
        else:
            self.dtype = data.dtype.name
        ctx = ctx or (self._ctx if self._ctx is not None
                      else current_context())
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._deferred_init = ()
        self._ctx = ctx[0]
        self._data = data.as_in_context(ctx[0])
        self._replicas = OrderedDict()
        for c in ctx[1:]:
            self._replicas[c] = self._data.as_in_context(c)
        if self._grad_req != "null":
            self._init_grad()

    # -- accessors ---------------------------------------------------------
    def _check_and_get(self, arr, ctx):
        if arr is not None:
            return arr
        if self._deferred_init:
            raise DeferredInitializationError(
                f"Parameter {self.name!r} has not been initialized yet "
                "because initialization was deferred. Actual initialization "
                "happens during the first forward pass.")
        raise MXNetError(
            f"Parameter {self.name!r} has not been initialized. You should "
            "initialize parameters with Block.initialize() before use.")

    def data(self, ctx=None) -> NDArray:
        d = self._check_and_get(self._data, ctx)
        if ctx is not None and isinstance(ctx, Context) and ctx != d.context:
            rep = self._replicas.get(ctx)
            if rep is not None:
                return rep
            raise MXNetError(
                f"Parameter {self.name!r} was not initialized on context "
                f"{ctx}. It was only initialized on {self.list_ctx()}.")
        return d

    def list_data(self) -> List[NDArray]:
        self._check_and_get(self._data, None)
        return self._all_replicas()

    def grad(self, ctx=None) -> NDArray:
        if self._data is not None and self._grad is None:
            raise MXNetError(
                f"Cannot get gradient array for Parameter {self.name!r} "
                "because grad_req='null'")
        g = self._check_and_get(self._grad, ctx)
        if ctx is not None and isinstance(ctx, Context) and ctx != self._ctx:
            rep = self._replicas.get(ctx)
            if rep is None:
                raise MXNetError(
                    f"Parameter {self.name!r} was not initialized on "
                    f"context {ctx}.")
            return rep._grad
        return g

    def list_grad(self) -> List[NDArray]:
        self.grad()
        return [arr._grad for arr in self._all_replicas()]

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init:
                return self._deferred_init[1]
            raise MXNetError(f"Parameter {self.name!r} has not been "
                             "initialized")
        return [self._ctx] + list(self._replicas.keys())

    def zero_grad(self):
        if self._grad is None:
            return
        for arr in self._all_replicas():
            if arr._grad is not None:
                arr._grad[:] = 0

    def set_data(self, data):
        self.shape = data.shape
        if self._data is None:
            assert self._deferred_init, \
                f"Parameter {self.name!r} has not been initialized"
            self._deferred_init = self._deferred_init[:3] + (data,)
            return
        if isinstance(data, NDArray):
            src = data
        else:
            src = nd.array(data, dtype=self.dtype)
        # buffer swap preserves the autograd leaf & grad buffer
        self._data._set_data(src._data.astype(self._data.dtype.name))
        for rep in self._replicas.values():
            src.copyto(rep)

    def reset_ctx(self, ctx):
        ctx = [ctx] if isinstance(ctx, Context) else list(ctx)
        if self._data is not None:
            self._data = self._data.as_in_context(ctx[0])
            self._ctx = ctx[0]
            self._replicas = OrderedDict(
                (c, self._data.as_in_context(c)) for c in ctx[1:])
            if self._grad_req != "null":
                self._init_grad()
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)
        else:
            raise MXNetError(f"Cannot reset context for Parameter "
                             f"{self.name!r} because it has not been "
                             "initialized.")

    def cast(self, dtype):
        self.dtype = np.dtype(dtype).name
        if self._data is None:
            return
        data = self._data.astype(dtype)
        self._data = data
        self._replicas = OrderedDict(
            (c, data.as_in_context(c)) for c in self._replicas)
        if self._grad_req != "null":
            self._init_grad()

    def var(self):
        """Symbol variable for this parameter (symbolic tracing)."""
        if self._var is None:
            from .. import symbol
            self._var = symbol.var(self.name, shape=self.shape,
                                   dtype=self.dtype)
        return self._var


def builtins_any(shape):
    return shape is not None


class Constant(Parameter):
    """Non-differentiable constant parameter (parity: gluon.Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(np.asarray(value), dtype=np.asarray(
                np.asarray(value)).dtype if hasattr(value, "dtype")
                else "float32")

        class _CInit(initializer.Initializer):
            def _init_weight(self, _, arr):
                arr[...] = value.asnumpy()

        self.value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype.name, init=_CInit(),
                         differentiable=False)


class ParameterDict:
    """Ordered prefix-scoped dict of Parameters (parity: ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        s = "\n".join(f"  {v}" for v in self._params.values())
        return f"ParameterDict {self._prefix} (\n{s}\n)"

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs) -> Parameter:
        """Get or create Parameter ``self.prefix + name``."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if k in ("stype", "grad_stype"):
                    # stored under private names; keep the shared param's
                    # sparse typing if EITHER declaration requests it
                    attr = "_" + k
                    if v is not None and v != "default":
                        if getattr(param, attr) in (None, "default"):
                            setattr(param, attr, v)
                            # already-initialized shared params must
                            # re-attach so the grad buffer gets typed
                            if k == "grad_stype" and \
                                    param._data is not None and \
                                    param._grad_req != "null":
                                param._init_grad()
                        elif getattr(param, attr) != v:
                            raise ValueError(
                                f"Parameter {name!r}: conflicting {k} "
                                f"{getattr(param, attr)!r} vs {v!r}")
                    continue
                existing = getattr(param, k, None)
                if existing is None or v is None:
                    if v is not None:
                        setattr(param, k, v)
                    continue
                if k == "shape":
                    # merge: 0 entries are wildcards; else must agree
                    if len(v) == len(existing) and all(
                            a == b or a == 0 or b == 0
                            for a, b in zip(existing, v)):
                        param._shape = tuple(
                            a if a != 0 else b
                            for a, b in zip(existing, v))
                        continue
                    raise AssertionError(
                        f"Cannot retrieve Parameter {name!r} because "
                        f"desired shape {v} conflicts with existing "
                        f"shape {existing}.")
                if k == "dtype":
                    if np.dtype(v) == np.dtype(existing):
                        continue
                    raise AssertionError(
                        f"Cannot retrieve Parameter {name!r} because "
                        f"desired dtype {v} conflicts with existing "
                        f"dtype {existing}.")
                # other attrs (init, grad_req, ...): first definition wins
                # only if identical; otherwise flag the conflict
                if existing != v and k not in ("init",):
                    raise AssertionError(
                        f"Cannot retrieve Parameter {name!r} because "
                        f"desired attribute {k}={v!r} conflicts with "
                        f"existing {existing!r}.")
        return param

    def get_constant(self, name, value=None) -> Constant:
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXNetError(
                    f"No constant named {name!r}. Please specify value if "
                    "you want to create a new constant.")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"Cannot update self with other because "
                                 f"they have different Parameters with the "
                                 f"same name {k!r}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = initializer.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            weight = param.data()
            if not param.name.startswith(strip_prefix):
                raise MXNetError(
                    f"Prefix {strip_prefix!r} is to be striped before "
                    f"saving, but Parameter {param.name!r} does not start "
                    "with it.")
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix="", cast_dtype=False):
        arg_dict = nd.load(filename)
        if restore_prefix:
            arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise MXNetError(
                        f"Parameter {name!r} is missing in file "
                        f"{filename!r}")
        for name, data in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError(
                        f"Parameter {name!r} loaded from file {filename!r} "
                        "is not present in this ParameterDict")
                continue
            self[name]._load_init(data, ctx, cast_dtype=cast_dtype)
