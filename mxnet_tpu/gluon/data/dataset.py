"""Datasets (parity: reference gluon/data/dataset.py)."""
from __future__ import annotations

from ...base import MXNetError
from ... import ndarray as nd

__all__ = ["Dataset", "ArrayDataset", "SimpleDataset", "RecordFileDataset"]


class Dataset:
    """Abstract dataset: __getitem__ + __len__."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def shard(self, num_shards, index):
        assert 0 <= index < num_shards
        length = len(self)
        shard_len = length // num_shards
        rest = length % num_shards
        start = shard_len * index + min(index, rest)
        end = start + shard_len + (index < rest)
        return _SlicedDataset(self, start, end)

    def take(self, count):
        return _SlicedDataset(self, 0, min(count, len(self)))

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        def base_fn(x, *args):
            if args:
                return (fn(x),) + args
            return fn(x)
        return self.transform(base_fn, lazy)


class _SlicedDataset(Dataset):
    def __init__(self, dataset, start, end):
        self._dataset = dataset
        self._start = start
        self._end = end

    def __len__(self):
        return self._end - self._start

    def __getitem__(self, idx):
        if idx >= len(self):
            raise IndexError
        return self._dataset[self._start + idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, dataset, fn):
        self._dataset = dataset
        self._fn = fn

    def __len__(self):
        return len(self._dataset)

    def __getitem__(self, idx):
        item = self._dataset[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class SimpleDataset(Dataset):
    """Wrap any indexable as a Dataset."""

    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zip of equal-length arrays/datasets (parity: ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            assert len(data) == self._length, \
                f"All arrays must have the same length; input {i} has " \
                f"length {len(data)} while the first has {self._length}."
            if isinstance(data, nd.NDArray) and data.ndim == 1:
                data = data.asnumpy()
            self._data.append(data)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(data[idx] for data in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """Dataset over an indexed RecordIO file."""

    def __init__(self, filename):
        from ... import recordio
        idx_file = filename[:filename.rfind(".")] + ".idx"
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record.keys)
