"""Vision transforms.

Capability parity: reference ``gluon/data/vision/transforms.py``
(ToTensor, Normalize, Resize, CenterCrop, RandomResizedCrop,
RandomFlipLeftRight/TopBottom, Cast, Compose).  Transforms run per-sample
on host (HWC uint8 → CHW float32), matching the reference's CPU augment
stage that feeds the device pipeline (SURVEY.md §2.4).
"""
from __future__ import annotations

import numpy as np

from ....base import MXNetError
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential
from .... import ndarray as nd
from ....ndarray.ndarray import NDArray

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast"]


def _asnp(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


class Compose(Sequential):
    """Sequentially compose transforms."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] → CHW float32 [0,1] (parity: ToTensor)."""

    def forward(self, x):
        img = _asnp(x).astype("float32") / 255.0
        if img.ndim == 3:
            img = img.transpose(2, 0, 1)
        elif img.ndim == 4:
            img = img.transpose(0, 3, 1, 2)
        return nd.array(img)


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, dtype="float32")
        self._std = np.asarray(std, dtype="float32")

    def forward(self, x):
        img = _asnp(x).astype("float32")
        mean = self._mean.reshape(-1, 1, 1)
        std = self._std.reshape(-1, 1, 1)
        return nd.array((img - mean) / std)


def _resize_np(img, size, interp="linear"):
    """Host bilinear/nearest resize of HWC image via jax.image."""
    import jax
    h, w = size[1], size[0]
    out_shape = (h, w, img.shape[2]) if img.ndim == 3 else (h, w)
    method = "linear" if interp != 0 else "nearest"
    return np.asarray(jax.image.resize(
        np.asarray(img, dtype="float32"), out_shape, method=method))


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        if isinstance(size, int):
            size = (size, size)
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        img = _asnp(x)
        w, h = self._size
        if self._keep:
            # fit within the (w, h) box preserving aspect ratio
            ih, iw = img.shape[:2]
            scale = min(w / iw, h / ih)
            h, w = max(int(ih * scale), 1), max(int(iw * scale), 1)
        out = _resize_np(img, (w, h), self._interpolation)
        return nd.array(out.astype("float32") if img.dtype != np.uint8
                        else np.clip(out, 0, 255).astype("uint8"),
                        dtype=("uint8" if img.dtype == np.uint8
                               else "float32"))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        if isinstance(size, int):
            size = (size, size)
        self._size = size
        self._interpolation = interpolation

    def forward(self, x):
        img = _asnp(x)
        w, h = self._size
        ih, iw = img.shape[:2]
        if ih < h or iw < w:
            img = _resize_np(img, (max(w, iw), max(h, ih)),
                             self._interpolation)
            ih, iw = img.shape[:2]
        y0 = (ih - h) // 2
        x0 = (iw - w) // 2
        out = img[y0:y0 + h, x0:x0 + w]
        return nd.array(out, dtype=str(np.asarray(out).dtype))


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        if isinstance(size, int):
            size = (size, size)
        self._size = size
        self._scale = scale
        self._ratio = ratio
        self._interpolation = interpolation

    def forward(self, x):
        img = _asnp(x)
        ih, iw = img.shape[:2]
        area = ih * iw
        for _ in range(10):
            target_area = np.random.uniform(*self._scale) * area
            log_ratio = (np.log(self._ratio[0]), np.log(self._ratio[1]))
            aspect = np.exp(np.random.uniform(*log_ratio))
            w = int(round(np.sqrt(target_area * aspect)))
            h = int(round(np.sqrt(target_area / aspect)))
            if w <= iw and h <= ih:
                x0 = np.random.randint(0, iw - w + 1)
                y0 = np.random.randint(0, ih - h + 1)
                crop = img[y0:y0 + h, x0:x0 + w]
                out = _resize_np(crop, self._size, self._interpolation)
                return nd.array(np.clip(out, 0, 255).astype(img.dtype),
                                dtype=str(img.dtype))
        # fallback: center crop
        return CenterCrop(self._size, self._interpolation).forward(
            nd.array(img, dtype=str(img.dtype)))


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            img = _asnp(x)
            return nd.array(img[:, ::-1].copy(), dtype=str(img.dtype))
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            img = _asnp(x)
            return nd.array(img[::-1].copy(), dtype=str(img.dtype))
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._args = (max(0, 1 - brightness), 1 + brightness)

    def forward(self, x):
        alpha = np.random.uniform(*self._args)
        img = _asnp(x).astype("float32") * alpha
        return nd.array(np.clip(img, 0, 255).astype("uint8"), dtype="uint8")


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._args = (max(0, 1 - contrast), 1 + contrast)

    def forward(self, x):
        alpha = np.random.uniform(*self._args)
        img = _asnp(x).astype("float32")
        gray = img.mean()
        img = gray + alpha * (img - gray)
        return nd.array(np.clip(img, 0, 255).astype("uint8"), dtype="uint8")
