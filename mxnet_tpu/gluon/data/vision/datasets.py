"""Vision datasets.

Capability parity: reference ``gluon/data/vision/datasets.py`` (MNIST,
FashionMNIST, CIFAR10/100, ImageFolderDataset, ImageRecordDataset).  This
environment has no network: datasets read pre-downloaded files from
``root`` when present, and every class supports ``synthetic=N`` to
generate a deterministic fake split of N samples with the real
shapes/dtypes — the equivalent of the reference's dummy-iter benchmarking
path (SURVEY.md §4 fixtures), and what CI uses.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ....base import MXNetError
from ... import data as _data_mod
from ..dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageFolderDataset", "ImageRecordDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform, synthetic=None):
        self._transform = transform
        self._train = train
        self._data = None
        self._label = None
        self._synthetic = synthetic
        root = os.path.expanduser(root)
        self._root = root
        if synthetic is None and not os.path.isdir(root):
            os.makedirs(root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        from .... import ndarray as nd
        img = nd.array(self._data[idx], dtype=self._data.dtype.name)
        label = self._label[idx]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST (28x28x1 uint8 HWC images, int32 labels)."""

    _shape = (28, 28, 1)
    _classes = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None, synthetic=None):
        self._train_data = ("train-images-idx3-ubyte.gz",)
        self._train_label = ("train-labels-idx1-ubyte.gz",)
        self._test_data = ("t10k-images-idx3-ubyte.gz",)
        self._test_label = ("t10k-labels-idx1-ubyte.gz",)
        super().__init__(root, train, transform, synthetic)

    def _get_data(self):
        if self._synthetic is not None:
            rng = np.random.RandomState(42 if self._train else 43)
            n = self._synthetic
            self._data = rng.randint(
                0, 256, (n,) + self._shape).astype(np.uint8)
            self._label = rng.randint(0, self._classes, n).astype(np.int32)
            return
        data_file = (self._train_data if self._train
                     else self._test_data)[0]
        label_file = (self._train_label if self._train
                      else self._test_label)[0]
        data_path = os.path.join(self._root, data_file)
        label_path = os.path.join(self._root, label_file)
        for p in (data_path, label_path):
            if not os.path.exists(p) and not os.path.exists(p[:-3]):
                raise MXNetError(
                    f"{p} not found and no network access; place the file "
                    f"there or pass synthetic=N for generated data")

        def _open(p):
            if os.path.exists(p):
                return gzip.open(p, "rb")
            return open(p[:-3], "rb")

        with _open(label_path) as fin:
            struct.unpack(">II", fin.read(8))
            label = np.frombuffer(fin.read(), dtype=np.uint8) \
                .astype(np.int32)
        with _open(data_path) as fin:
            struct.unpack(">IIII", fin.read(16))
            data = np.frombuffer(fin.read(), dtype=np.uint8)
            data = data.reshape(len(label), 28, 28, 1)
        self._data = data
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None, synthetic=None):
        super().__init__(root, train, transform, synthetic)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 (32x32x3 uint8 HWC images, int32 labels)."""

    _shape = (32, 32, 3)
    _classes = 10
    _archive = "cifar-10-binary.tar.gz"

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar10"),
                 train=True, transform=None, synthetic=None):
        super().__init__(root, train, transform, synthetic)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = np.frombuffer(fin.read(), dtype=np.uint8).reshape(
                -1, 3072 + 1)
        return data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0].astype(np.int32)

    def _get_data(self):
        if self._synthetic is not None:
            rng = np.random.RandomState(44 if self._train else 45)
            n = self._synthetic
            self._data = rng.randint(
                0, 256, (n,) + self._shape).astype(np.uint8)
            self._label = rng.randint(0, self._classes, n).astype(np.int32)
            return
        if self._train:
            files = [os.path.join(self._root, f"data_batch_{i}.bin")
                     for i in range(1, 6)]
        else:
            files = [os.path.join(self._root, "test_batch.bin")]
        for f in files:
            if not os.path.exists(f):
                raise MXNetError(
                    f"{f} not found and no network access; place CIFAR "
                    "binary batches there or pass synthetic=N")
        data, label = zip(*[self._read_batch(f) for f in files])
        self._data = np.concatenate(data)
        self._label = np.concatenate(label)


class CIFAR100(CIFAR10):
    _classes = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None,
                 synthetic=None):
        self._fine_label = fine_label
        super().__init__(root, train, transform, synthetic)


class ImageFolderDataset(Dataset):
    """A dataset of images arranged as root/class/xxx.png.

    Decoding uses whatever host decoders are available (PNG/PPM via
    NumPy; JPEG requires an image library, documented as a gap when
    absent).
    """

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".png", ".ppm", ".npy"]
        self.synsets = []
        self.items = []
        self._list_images(self._root)

    def _list_images(self, root):
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                ext = os.path.splitext(filename)[1].lower()
                if ext in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from .... import ndarray as nd
        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = np.load(path)
        else:
            from .... import image
            img = image.imread(path, self._flag).asnumpy()
        img = nd.array(img, dtype="uint8")
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class ImageRecordDataset(Dataset):
    """Dataset over a RecordIO file of packed images (im2rec output)."""

    def __init__(self, filename, flag=1, transform=None):
        from .... import recordio
        idx_file = filename[:filename.rfind(".")] + ".idx"
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import ndarray as nd
        from .... import recordio as rio
        record = self._record.read_idx(self._record.keys[idx])
        header, img_bytes = rio.unpack(record)
        label = header.label
        img = rio.imdecode_raw(img_bytes)
        img = nd.array(img, dtype="uint8")
        if self._transform is not None:
            return self._transform(img, label)
        return img, np.float32(label)

    def __len__(self):
        return len(self._record.keys)
