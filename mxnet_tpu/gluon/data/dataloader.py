"""DataLoader.

Capability parity: reference ``python/mxnet/gluon/data/dataloader.py``
(SURVEY.md §2.4): batchify (default stack / user fn), samplers,
``num_workers`` parallel loading, pin_memory surface.  TPU-native detail:
worker parallelism uses a thread pool feeding host NumPy batches (the GIL
is released inside NumPy/decoding), because device placement must stay on
the main thread with PJRT; the reference's fork-based workers + shared-mem
NDArray IPC exist to feed GPUs from Python, which XLA's async host→device
copies already cover.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ... import ndarray as nd
from ...ndarray.ndarray import NDArray
from .sampler import SequentialSampler, RandomSampler, BatchSampler
from .dataset import Dataset

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (parity: default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return nd.array(data, dtype=data.dtype)


default_mp_batchify_fn = default_batchify_fn


class DataLoader:
    """Loads data from a Dataset and returns mini-batches."""

    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, pin_memory=False,
                 pin_device_id=0, prefetch=None, thread_pool=False,
                 timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        self._timeout = timeout

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(
                sampler, batch_size,
                last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._batchify_fn = batchify_fn if batchify_fn is not None \
            else default_batchify_fn
        # worker jobs run on the native C++ engine when built
        # (engine.pipeline.io_pool); ThreadPoolExecutor is the fallback
        from ...engine.pipeline import io_pool
        self._pool = io_pool(self._num_workers) \
            if self._num_workers > 0 else None

    def __iter__(self):
        if self._pool is None:
            for batch in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in batch])
            return
        # pipelined: submit sample fetches ahead, assemble in order
        def fetch(batch):
            return self._batchify_fn([self._dataset[i] for i in batch])
        batches = list(self._batch_sampler)
        futures = []
        depth = self._num_workers * 2
        it = iter(batches)
        for _ in range(min(depth, len(batches))):
            futures.append(self._pool.submit(fetch, next(it)))
        done = 0
        while futures:
            f = futures.pop(0)
            try:
                nxt = next(it)
                futures.append(self._pool.submit(fetch, nxt))
            except StopIteration:
                pass
            yield f.result(timeout=self._timeout)
            done += 1

    def __len__(self):
        return len(self._batch_sampler)
