"""DataLoader.

Capability parity: reference ``python/mxnet/gluon/data/dataloader.py``
(SURVEY.md §2.4): batchify (default stack / user fn), samplers,
``num_workers`` parallel loading, pin_memory surface.  TPU-native detail:
worker parallelism uses a thread pool feeding host NumPy batches (the GIL
is released inside NumPy/decoding), because device placement must stay on
the main thread with PJRT; the reference's fork-based workers + shared-mem
NDArray IPC exist to feed GPUs from Python, which XLA's async host→device
copies already cover.

Pipelining, two stages (both optional, both teleport worker exceptions
to the consumer at the batch they poisoned):

* sample fetch/decode (the IO-bound stage) runs ``prefetch`` batches
  ahead on ``engine.pipeline.io_pool`` (the native C++ engine when
  built, ``MXTPU_NATIVE_IO=0`` falls back to Python threads) — the
  reference's worker prefetch; ``batchify_fn`` itself runs on the
  consumer thread, since it creates device arrays.  Dataset
  ``__getitem__`` should therefore stay host-side (IO / decode /
  numpy; lazy NDArray views are fine) — dispatching device ops from
  worker threads is unsupported with PJRT;
* ``prefetch_to_device`` additionally stages the next
  ``MXTPU_PREFETCH_DEPTH`` batches onto the device from the CONSUMER
  thread (PJRT placement must not move off it): the copy is issued
  asynchronously before the previous batch is consumed, so host→device
  transfer overlaps device execution — the reference's
  ``iter_prefetcher.h`` double buffering, rebuilt on XLA's async
  transfers.
"""
from __future__ import annotations

from collections import deque

import numpy as np

from ...base import MXNetError
from ... import ndarray as nd
from ...ndarray.ndarray import NDArray
from .sampler import SequentialSampler, RandomSampler, BatchSampler
from .dataset import Dataset

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (parity: default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return nd.array(data, dtype=data.dtype)


default_mp_batchify_fn = default_batchify_fn


def _to_device(batch, ctx):
    """Issue the (async) host→device copy for every NDArray in a batch."""
    if isinstance(batch, NDArray):
        return batch.as_in_context(ctx)
    if isinstance(batch, (list, tuple)):
        moved = [_to_device(b, ctx) for b in batch]
        return moved if isinstance(batch, list) else tuple(moved)
    return batch


class DataLoader:
    """Loads data from a Dataset and returns mini-batches.

    ``prefetch``: how many batches the worker pool assembles ahead of
    the consumer (default ``2 * num_workers``; with ``num_workers=0`` a
    positive value spins up a single io_pool worker so prefetching
    still overlaps).  ``prefetch_to_device``: a Context (or True for
    the current context) to double-buffer finished batches onto, so the
    host→device copy of batch i+1 is in flight while batch i trains;
    None reads the ``MXTPU_PREFETCH_TO_DEVICE`` default.
    """

    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, pin_memory=False,
                 pin_device_id=0, prefetch=None, thread_pool=False,
                 timeout=120, prefetch_to_device=None):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        self._timeout = timeout

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(
                sampler, batch_size,
                last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)
        if prefetch_to_device is None:
            from ... import envs
            prefetch_to_device = envs.get("MXTPU_PREFETCH_TO_DEVICE")
        self._prefetch_ctx = prefetch_to_device
        self._batchify_fn = batchify_fn if batchify_fn is not None \
            else default_batchify_fn
        # worker jobs run on the native C++ engine when built
        # (engine.pipeline.io_pool); ThreadPoolExecutor is the fallback
        from ...engine.pipeline import io_pool
        if self._num_workers > 0:
            self._pool = io_pool(self._num_workers)
        elif self._prefetch > 0:
            # explicit prefetch without workers: one pipeline worker
            # still overlaps batch assembly with consumption
            self._pool = io_pool(1)
        else:
            self._pool = None

    def __iter__(self):
        it = self._iter_batches()
        ctx = self._prefetch_ctx
        if ctx:
            if ctx is True:
                from ...context import current_context
                ctx = current_context()
            from ... import envs
            depth = max(1, envs.get("MXTPU_PREFETCH_DEPTH"))
            it = self._iter_device_prefetch(it, ctx, depth)
        return it

    def _iter_batches(self):
        if self._pool is None:
            for batch in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in batch])
            return
        # pipelined: workers fetch/decode the samples (the IO-bound
        # stage) ahead of the consumer; batchify — which creates DEVICE
        # arrays — runs on the consumer thread, because concurrent
        # device_put from pool threads crashes PJRT (placement must
        # stay on one thread; observed segfault with 2+ pools active)
        import time
        from ... import telemetry

        def fetch(batch):
            t0 = time.perf_counter()
            out = [self._dataset[i] for i in batch]
            # producer-side work time, recorded FROM the worker thread
            # (the registry lock is the only shared state touched)
            telemetry.histogram(
                "mxtpu_dataloader_fetch_seconds",
                "worker fetch/decode time per batch (s)"
                ).observe(time.perf_counter() - t0)
            return out
        batches = list(self._batch_sampler)
        futures = []
        depth = max(1, self._prefetch)
        it = iter(batches)
        for _ in range(min(depth, len(batches))):
            futures.append(self._pool.submit(fetch, next(it)))
        stall_counter = telemetry.counter(
            "mxtpu_prefetch_stalls_total",
            "batches the consumer had to WAIT for (queue was dry)")
        batch_counter = telemetry.counter(
            "mxtpu_dataloader_batches_total",
            "batches consumed through the prefetch pipeline")
        depth_gauge = telemetry.gauge(
            "mxtpu_prefetch_queue_depth",
            "batches in flight in the worker pool")
        wait_hist = telemetry.histogram(
            "mxtpu_dataloader_consumer_wait_seconds",
            "consumer-side wait for the next batch (s)")
        first = True
        while futures:
            f = futures.pop(0)
            try:
                nxt = next(it)
                futures.append(self._pool.submit(fetch, nxt))
            except StopIteration:
                pass
            depth_gauge.set(len(futures))
            # stall attribution must be decided BEFORE blocking: a
            # not-yet-done future here means the pipeline failed to
            # stay ahead of the consumer (input-bound signature).
            # The FIRST batch is exempt — the consumer arrives the
            # instant the pipeline was seeded, so batch 1 of every
            # epoch would read as a stall even in a healthy pipeline
            stalled = not first and not f.done()
            first = False
            t0 = time.perf_counter()
            # a worker exception teleports out of result() here, AT the
            # batch it poisoned — reference exception-at-sync semantics
            samples = f.result(timeout=self._timeout)
            wait = time.perf_counter() - t0
            wait_hist.observe(wait)
            batch_counter.inc()
            if stalled:
                stall_counter.inc()
                telemetry.record_event(
                    "prefetch_stall", wait_s=round(wait, 6),
                    queue_depth=len(futures))
            yield self._batchify_fn(samples)

    @staticmethod
    def _iter_device_prefetch(it, ctx, depth):
        """Double-buffered device staging: keep ``depth`` batches'
        host→device copies in flight ahead of the consumer.  Runs on
        the consumer thread (PJRT placement stays where it must); the
        overlap comes from the copies being asynchronous."""
        from ... import telemetry
        occupancy = telemetry.gauge(
            "mxtpu_device_staging_occupancy",
            "batches currently staged on the device ahead of the "
            "consumer (MXTPU_PREFETCH_DEPTH budget)")
        buf = deque()
        try:
            while len(buf) < depth:
                buf.append(_to_device(next(it), ctx))
        except StopIteration:
            pass
        while buf:
            # pop BEFORE refilling so at most `depth` batches are ever
            # device-resident (the documented MXTPU_PREFETCH_DEPTH HBM
            # budget); the refill copy is still issued before the yield
            # returns control, so it overlaps the consumer's compute
            out = buf.popleft()
            try:
                buf.append(_to_device(next(it), ctx))
            except StopIteration:
                pass
            occupancy.set(len(buf))
            yield out

    def __len__(self):
        return len(self._batch_sampler)

    def __del__(self):
        # the loader owns its pool: release the worker threads (and the
        # native engine, when active) deterministically instead of at
        # interpreter shutdown
        pool = getattr(self, "_pool", None)
        if pool is not None:
            try:
                pool.shutdown(wait=False)
            except Exception:
                pass
