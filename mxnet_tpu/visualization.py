"""``mx.viz`` — network visualization (parity:
``python/mxnet/visualization.py``): ``print_summary`` renders the
layer table with per-layer output shapes and parameter counts;
``plot_network`` emits a graphviz Digraph of the symbol DAG.  Both
read the same serialized graph (``Symbol.tojson``) the executor uses.
"""
from __future__ import annotations

import json

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]

_PARAM_SUFFIXES = ("weight", "bias", "gamma", "beta", "moving_mean",
                   "moving_var", "running_mean", "running_var")


def _graph(symbol):
    g = json.loads(symbol.tojson())
    return g["nodes"], g["heads"]


def _infer(symbol, shape):
    """All internal output shapes keyed by output name, or {} when
    inference cannot complete (infer_shape_partial yields None)."""
    if not shape:
        return {}
    internals = symbol.get_internals()
    names = internals.list_outputs()
    _, shapes, _ = internals.infer_shape_partial(**shape)
    if shapes is None:
        return {}
    return dict(zip(names, shapes))


def _make_is_param(inputs):
    def is_param(node):
        # a null node is a PARAMETER unless the caller listed it as an
        # input; without shapes, fall back to conventional suffixes
        if node["op"] != "null":
            return False
        if inputs:
            return node["name"] not in inputs
        return node["name"].endswith(_PARAM_SUFFIXES)
    return is_param


def _out_shape(shapes, name):
    """Probe the single- and multi-output key spellings."""
    for k in (name + "_output", name + "_output0", name):
        if k in shapes:
            return shapes[k]
    return ""


def print_summary(symbol, shape=None, line_length=98):
    """Layer-table summary (parity: ``mx.viz.print_summary``).

    ``shape``: dict of input name -> shape, forwarded to
    ``infer_shape`` so the table carries real output shapes and exact
    parameter counts."""
    nodes, _ = _graph(symbol)
    inputs = set(shape or ())
    out_shapes = _infer(symbol, shape)
    is_param = _make_is_param(inputs)

    def n_params(node):
        # variable nodes appear in the internals outputs by plain
        # name, so one inference pass serves both columns
        total = 0
        for i_idx, *_ in node["inputs"]:
            src = nodes[i_idx]
            if is_param(src):
                shp = out_shapes.get(src["name"])
                if shp:
                    p = 1
                    for d in shp:
                        p *= int(d)
                    total += p
        return total

    hdr = f"{'Layer (type)':<34}{'Output Shape':<26}" \
          f"{'Param #':>10}  Connected to"
    lines = ["_" * line_length, hdr, "=" * line_length]
    total_params = 0
    for node in nodes:
        if node["op"] == "null":
            continue
        name = node["name"]
        oshape = _out_shape(out_shapes, name)
        p = n_params(node)
        total_params += p
        ins = ", ".join(
            nodes[i]["name"] for i, *_ in node["inputs"]
            if nodes[i]["op"] != "null")
        lines.append(f"{name + ' (' + node['op'] + ')':<34}"
                     f"{str(oshape):<26}{p:>10}  {ins}")
    lines += ["=" * line_length,
              f"Total params: {total_params:,}",
              "_" * line_length]
    out = "\n".join(lines)
    print(out)
    return out


_FILL = {"Convolution": "#4f8dd1", "Deconvolution": "#4f8dd1",
         "FullyConnected": "#cd6155", "BatchNorm": "#58d68d",
         "LayerNorm": "#58d68d", "Activation": "#f5b041",
         "Pooling": "#af7ac5", "softmax": "#5dade2",
         "SoftmaxOutput": "#5dade2"}


def plot_network(symbol, title="plot", shape=None,
                 node_attrs=None, save_format="pdf"):
    """Graphviz Digraph of the symbol DAG (parity:
    ``mx.viz.plot_network``); call ``.render()`` / ``.view()`` on the
    result, or access ``.source`` for the dot text."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError("plot_network requires the graphviz "
                         "package") from e
    nodes, heads = _graph(symbol)
    inputs = set(shape or ())
    shape_info = _infer(symbol, shape)
    is_param = _make_is_param(inputs)

    dot = Digraph(name=title, format=save_format)
    base_attrs = {"shape": "box", "fixedsize": "false",
                  "style": "rounded,filled"}
    base_attrs.update(node_attrs or {})
    for idx, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            if not is_param(node):
                dot.node(str(idx), name, **dict(
                    base_attrs, fillcolor="#eeeeee", shape="oval"))
            continue
        label = f"{name}\\n{op}"
        attrs = node.get("attrs") or {}
        for k in ("kernel", "stride", "num_hidden", "num_filter",
                  "act_type", "pool_type"):
            if k in attrs:
                label += f"\\n{k}={attrs[k]}"
        dot.node(str(idx), label, **dict(
            base_attrs, fillcolor=_FILL.get(op, "#d5dbdb")))
        for i_idx, *_ in node["inputs"]:
            src = nodes[i_idx]
            if is_param(src):
                continue
            edge_label = ""
            shp = _out_shape(shape_info, src["name"]) \
                if src["op"] != "null" else shape_info.get(src["name"])
            if shp:
                edge_label = "x".join(str(d) for d in shp[1:]) or "1"
            dot.edge(str(i_idx), str(idx), label=edge_label)
    return dot
