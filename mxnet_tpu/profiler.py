"""Profiler (parity: ``python/mxnet/profiler.py`` over
``src/profiler/profiler.cc`` — SURVEY.md §5 "Tracing / profiling").

Two layers, mirroring the reference's engine-wired profiler:

* **Op events** — the engine's dispatch path is intercepted
  (``engine._profiler_hook``) while the profiler runs; each op records a
  host-side span (dispatch → ready when ``MXTPU_PROFILE_SYNC=1``, else
  async dispatch span).  ``dump()`` writes chrome://tracing JSON,
  ``dumps()`` an aggregate table — the same artifacts the reference
  produced.
* **Device traces** — ``profile_device=True`` brackets the run with
  ``jax.profiler`` (XPlane/TensorBoard), the TPU-native replacement for
  the reference's device timelines.

Custom scopes: ``Marker``, ``record_scope`` map to instant events /
ranges, and also forward to ``jax.profiler.TraceAnnotation`` so they show
up inside XPlane traces.
"""
from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import List, Optional

from .base import MXNetError
from . import engine

__all__ = ["set_config", "set_state", "state", "pause", "resume", "dump",
           "dumps", "Marker", "record_scope"]

_lock = threading.Lock()
_events: List[dict] = []
_state = "stop"
_paused = False
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "profile_device": False,
    "aggregate_stats": False,
    "device_logdir": "/tmp/mxtpu_xplane",
}
_device_trace_active = False
_t0 = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def set_config(**kwargs):
    """Configure (parity: profiler.set_config)."""
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise MXNetError(f"unknown profiler config keys {sorted(unknown)}")
    _config.update(kwargs)


def _record_event(name, cat, start_us, end_us):
    """Append one chrome-trace complete event (shared schema)."""
    if _paused:
        return
    with _lock:
        _events.append({"name": name, "ph": "X", "ts": start_us,
                        "dur": end_us - start_us, "pid": 0,
                        "tid": threading.get_ident() % 100000,
                        "cat": cat})


def _maybe_block(out):
    """MXTPU_PROFILE_SYNC=1: block on outputs so spans measure device
    time, not async dispatch."""
    from . import envs
    if envs.get("MXTPU_PROFILE_SYNC"):
        import jax
        try:
            jax.block_until_ready(out)
        except Exception:
            pass  # non-array outputs (vjp closures) can't be awaited


def _hook(name, fn, arrays):
    start = _now_us()
    out = fn(*arrays)
    _maybe_block(out)
    _record_event(name, "operator", start, _now_us())
    return out


def set_state(state_name="stop", profile_process="worker"):
    """'run' starts collection; 'stop' ends it (parity:
    profiler.set_state)."""
    global _state, _device_trace_active
    if state_name not in ("run", "stop"):
        raise MXNetError("state must be 'run' or 'stop'")
    if state_name == "run" and _state != "run":
        engine._profiler_hook = _hook
        if _config["profile_device"]:
            import jax
            jax.profiler.start_trace(_config["device_logdir"])
            _device_trace_active = True
    elif state_name == "stop" and _state != "stop":
        engine._profiler_hook = None
        if _device_trace_active:
            import jax
            jax.profiler.stop_trace()
            _device_trace_active = False
    _state = state_name


def state():
    return _state


def pause(profile_process="worker"):
    global _paused
    _paused = True


def resume(profile_process="worker"):
    global _paused
    _paused = False


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON to the configured filename."""
    with _lock:
        events = list(_events)
        if finished:
            _events.clear()
    with open(_config["filename"], "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)


def dumps(reset=False, format_="table"):
    """Aggregate per-op stats (parity: profiler.dumps).

    ``format_="table"`` renders the classic fixed-width text table;
    ``format_="json"`` returns the same aggregates as a JSON object
    (``{"ops": {name: {calls, total_us, min_us, max_us, avg_us}}}``)
    for machine consumers.  Unknown formats raise ``MXNetError`` —
    the parameter was previously accepted and silently ignored.
    """
    if format_ not in ("table", "json"):
        raise MXNetError(
            f"unknown dumps format {format_!r} (want 'table' or 'json')")
    with _lock:
        events = list(_events)
        if reset:
            _events.clear()
    agg = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
    for e in events:
        if "dur" not in e:
            continue          # instant events carry no span to total
        a = agg[e["name"]]
        a[0] += 1
        a[1] += e["dur"]
        a[2] = min(a[2], e["dur"])
        a[3] = max(a[3], e["dur"])
    if format_ == "json":
        return json.dumps({"ops": {
            name: {"calls": n, "total_us": round(tot, 1),
                   "min_us": round(mn, 1), "max_us": round(mx, 1),
                   "avg_us": round(tot / n, 1)}
            for name, (n, tot, mn, mx) in sorted(
                agg.items(), key=lambda kv: -kv[1][1])}})
    lines = [f"{'Name':<40}{'Calls':>8}{'Total(us)':>14}{'Min(us)':>12}"
             f"{'Max(us)':>12}{'Avg(us)':>12}"]
    for name, (n, tot, mn, mx) in sorted(agg.items(),
                                         key=lambda kv: -kv[1][1]):
        lines.append(f"{name:<40}{n:>8}{tot:>14.1f}{mn:>12.1f}"
                     f"{mx:>12.1f}{tot / n:>12.1f}")
    return "\n".join(lines)


def active() -> bool:
    """True while collection runs (cheap guard for call sites)."""
    return _state == "run" and not _paused


def _mirror_event(name, args=None):
    """Telemetry mirror: one instant event in the chrome-trace stream
    for a structured telemetry event (retrace, prefetch stall, poison),
    so a single timeline shows op spans AND the telemetry plane's
    annotations.  Only called while :func:`active`."""
    if not active():
        return
    with _lock:
        _events.append({"name": name, "ph": "i", "ts": _now_us(),
                        "pid": 0,
                        "tid": threading.get_ident() % 100000,
                        "s": "p", "cat": "telemetry",
                        "args": dict(args) if args else {}})


class _span:
    """Internal span recorder for framework call sites (CachedOp,
    Executor, DataParallelTrainer) — the reference wired its profiler
    INSIDE ExecuteOprBlock; these are the jit-path equivalents that the
    imperative hook cannot see.  Cheap enough to enter unconditionally;
    the event is only recorded while collection is active.  Call
    ``sync(out)`` on the produced arrays before leaving the block so
    MXTPU_PROFILE_SYNC measures device time like the imperative hook.
    """

    __slots__ = ("name", "cat", "_start")

    def __init__(self, name, cat):
        self.name = name
        self.cat = cat

    def __enter__(self):
        self._start = _now_us()
        return self

    def sync(self, out):
        if active():
            _maybe_block(out)

    def __exit__(self, *exc):
        if active():
            _record_event(self.name, self.cat, self._start, _now_us())


class Marker:
    """Custom instant marker (parity: profiler.Marker)."""

    def __init__(self, name, domain=None):
        self.name = name

    def mark(self, scope="process"):
        if _state == "run" and not _paused:
            with _lock:
                _events.append({"name": self.name, "ph": "i",
                                "ts": _now_us(), "pid": 0, "tid": 0,
                                "s": "p", "cat": "marker"})


class record_scope:
    """``with profiler.record_scope('step'):`` — a named range, also
    visible in XPlane traces."""

    def __init__(self, name):
        self.name = name
        self._jax_ctx = None

    def __enter__(self):
        self._start = _now_us()
        try:
            import jax
            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()
        except Exception:
            self._jax_ctx = None
        return self

    def __exit__(self, *exc):
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(*exc)
        if _state == "run" and not _paused:
            with _lock:
                _events.append({"name": self.name, "ph": "X",
                                "ts": self._start,
                                "dur": _now_us() - self._start,
                                "pid": 0, "tid": 0, "cat": "scope"})
