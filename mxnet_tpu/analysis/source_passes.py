"""Source passes (rules MXL3xx): retrace / host-sync hazard detection.

The Julia→TPU compiler paper's lesson applied at authoring time: on an
XLA target the expensive mistakes are *host synchronization inside the
step loop* (``asnumpy()`` forces a device round-trip per iteration,
serializing the async engine) and *Python-scalar static attrs that vary
per step* (every new value re-traces and re-compiles the kernel — the
"retrace storm").  Both are visible in the AST without running anything.

Heuristics are deliberately scoped to keep the signal high:

* MXL301 fires only inside loops that look like training loops (the loop
  body calls ``backward``/``step`` or opens ``autograd.record()``).
* MXL302 fires for syncs anywhere inside a ``hybrid_forward`` body —
  hybridized tracing turns these into per-call retraces or outright
  tracer errors.
* MXL303 fires when a registered op is called inside a loop with a
  *static* attr (keyword-only in the fcompute signature) whose value
  references a name the loop itself changes — the jit cache keys on the
  value, so each step compiles a fresh executable.  The fix is usually
  declaring the attr in ``scalar_attrs``.
* MXL311 specializes MXL301 for the most common offender: a per-step
  host scalar read of the LOSS or a metric (``loss.item()``,
  ``float(loss)``, ``loss.asnumpy()``, ``metric.get()``-feeding reads)
  inside a detected train loop.  Beyond the per-step device sync, the
  read is redundant — the training-health plane already computes the
  loss (plus grad/update norms and nonfinite counts) INSIDE the
  compiled step and samples it every ``MXTPU_HEALTH_EVERY`` steps
  (``telemetry.health``, docs/observability.md).  When the receiver
  names a loss/metric the finding is MXL311 (with the pointer),
  otherwise MXL301 as before.
* MXL304 fires for a classic per-op training loop —
  ``autograd.record()`` + ``.backward()`` + ``.step()`` in one loop
  body — in a module that never touches step compilation
  (``Trainer.compile_step`` / ``CompiledStep`` / the SPMD
  ``DataParallelTrainer``): a hybridize-eligible block there pays one
  dispatch per op when it could pay one per STEP (docs/compiled_step.md).
  Its runtime sibling MXL305 (``analyze_compiled_steps``) reports when
  a CompiledStep was requested but silently fell back to eager, with
  the recorded reason.

* MXL601 fires for a model-zoo ``prefill``/``decode_step``/
  ``generate`` call inside a loop — the per-request serving shape —
  in a module that never references the serving plane (``Server`` /
  ``KVCachePool`` / ``BucketScheduler`` / a ``serving`` import): each
  request pays its own prefill and per-op decode dispatches, and each
  unseen prompt length compiles fresh programs (docs/serving.md).
  Exempt: a model's own ``self.<method>`` loop, a loop-induction
  receiver (``for layer in self.layers``), and ``prefill``/
  ``decode_step`` in a ``range()`` loop (position stepping — the
  incremental-decode implementation, not a request loop).  Its
  runtime twin (``analyze_serving``) reports a serving bucket that
  kept compiling in steady state.

* MXL501 fires for a training loop that dispatches ``step``/
  ``step_multi`` at least ``_CKPT_LOOP_MIN_STEPS`` times (a statically
  known ``range`` bound, or an unbounded ``while True``) in a module
  that never references a checkpointing surface
  (``CheckpointManager`` / ``OrbaxCheckpoint`` / ``save_checkpoint``):
  one preemption or post-donation dispatch failure loses the whole
  run — docs/elasticity.md.  Its runtime sibling (``analyze_
  elasticity``) reports when N steps actually RAN in-process and no
  manager was ever constructed.

* MXL707 (mxsan's static leg) fires when a loop rebinds a variable
  from a call to a ``jax.jit``-compiled function that takes the SAME
  variable as an argument — ``params = step(params, batch)`` — and
  the ``jit`` construction (visible in the same module) has no
  ``donate_argnums``: the input is dead after the call, so a >=64MiB
  buffer there is double-buffered in HBM for nothing (the static twin
  of the runtime MXL308/309 checks; the engine's fused paths donate
  exactly this shape).

* MXL708 (mxsan's static leg) fires for a host sync (``.item()`` /
  ``float()`` / ``np.asarray()`` / ``.asnumpy()``) applied to a STEP
  OUTPUT — a name bound from a ``.step()``/``.step_multi()`` call in
  the same loop nest — inside the loop: a device round-trip per
  iteration on the training signal.  Loss/metric-named receivers keep
  reporting as MXL311 (the health-plane pointer); MXL708 covers the
  rest.

Suppress any rule on a line with ``# mxlint: disable=MXL301`` (comma-
separated IDs) or every rule with a bare ``# mxlint: disable``.
"""
from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Set

from .findings import Finding

__all__ = ["analyze_source", "analyze_file", "analyze_paths"]

_SYNC_METHODS = {"asnumpy", "asscalar", "wait_to_read", "item", "tolist"}
_CAST_BUILTINS = {"float", "int", "bool"}
# receivers that look like a loss/metric value: the MXL311
# specialization (per-step scalarization of the training signal the
# sampled health plane already provides)
_LOSS_NAME_RE = re.compile(r"loss|metric|perplexity", re.I)
_OP_NAMESPACES = {"nd", "F", "sym", "ndarray", "symbol"}
_DISABLE_RE = re.compile(r"#\s*mxlint:\s*disable(?:=([A-Z0-9,\s]+))?")
# any of these names in a module means the author already uses step
# compilation somewhere — MXL304 stays quiet for the whole file
_STEP_COMPILE_MARKERS = {"compile_step", "CompiledStep", "step_multi",
                         "DataParallelTrainer"}
# any of these in a module means checkpointing is wired up somewhere —
# MXL501 stays quiet for the whole file ("a CheckpointManager is in
# scope"); `recover` counts because calling it requires a manager
_CKPT_MARKERS = {"CheckpointManager", "OrbaxCheckpoint",
                 "save_checkpoint", "recover"}
# any of these in a module means the serving plane is in scope —
# MXL601 stays quiet for the whole file (the author already batches
# the decode path).  NOT `warm_start`: that name is shared with the
# PR 5 TRAINING warm start, and a train script using it can still
# loop per-request generate() — the exact hazard this rule exists for
_SERVING_MARKERS = {"Server", "serving", "KVCachePool",
                    "BucketScheduler"}
# model-zoo decode-contract calls that, inside a request loop, pay a
# per-request prefill + T per-op decode dispatches (and a fresh
# compile per UNSEEN prompt length) — the shape Server's fixed
# buckets amortize
_SERVING_CALLS = {"prefill", "decode_step", "generate",
                  "generate_fused"}
#: statically-known step counts below this never fire MXL501 — short
#: smoke/debug loops are not "a run worth checkpointing"
_CKPT_LOOP_MIN_STEPS = 100


def _attr_chain(node) -> List[str]:
    """['mx', 'nd', 'zeros'] for mx.nd.zeros; [] when not a plain chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _is_sync_call(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr in _SYNC_METHODS:
        return f".{call.func.attr}()"
    return None


def _names_loss(node) -> bool:
    """Does this expression reference a name/attribute that reads like
    a loss or metric value?"""
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and _LOSS_NAME_RE.search(n.id):
            return True
        if isinstance(n, ast.Attribute) and \
                _LOSS_NAME_RE.search(n.attr):
            return True
    return False


def _is_cast_sync(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Name) and \
            call.func.id in _CAST_BUILTINS and len(call.args) == 1 and \
            not isinstance(call.args[0], ast.Constant):
        return f"{call.func.id}(...)"
    return None


def _training_markers(loop) -> bool:
    for sub in ast.walk(loop):
        if isinstance(sub, ast.Call):
            if isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in ("backward", "step"):
                return True
            chain = _attr_chain(sub.func)
            if chain and chain[-1] == "record":
                return True
    return False


def _per_op_step_loop(loop) -> bool:
    """True for the full record+backward+step triple in one loop body —
    the shape ``Trainer.compile_step`` collapses to one dispatch."""
    has_record = has_backward = has_step = False
    for sub in ast.walk(loop):
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute):
                if f.attr == "backward":
                    has_backward = True
                elif f.attr == "step":
                    has_step = True
            chain = _attr_chain(f)
            if chain and chain[-1] == "record":
                has_record = True
    return has_record and has_backward and has_step


def _module_uses_step_compilation(tree) -> bool:
    for n in ast.walk(tree):
        if isinstance(n, ast.Attribute) and \
                n.attr in _STEP_COMPILE_MARKERS:
            return True
        if isinstance(n, ast.Name) and n.id in _STEP_COMPILE_MARKERS:
            return True
    return False


def _module_uses_checkpointing(tree) -> bool:
    for n in ast.walk(tree):
        if isinstance(n, ast.Attribute) and n.attr in _CKPT_MARKERS:
            return True
        if isinstance(n, ast.Name) and n.id in _CKPT_MARKERS:
            return True
    return False


def _module_uses_serving(tree) -> bool:
    for n in ast.walk(tree):
        if isinstance(n, ast.Attribute) and n.attr in _SERVING_MARKERS:
            return True
        if isinstance(n, ast.Name) and n.id in _SERVING_MARKERS:
            return True
        # `from mxnet_tpu.serving import Server` binds ast.alias
        # nodes, not Names — an import alone is already "the serving
        # plane is in scope"
        if isinstance(n, ast.ImportFrom):
            if "serving" in (n.module or "") or any(
                    a.name in _SERVING_MARKERS for a in n.names):
                return True
        elif isinstance(n, ast.Import):
            if any("serving" in a.name for a in n.names):
                return True
    return False


def _loop_trip_count(loop) -> Optional[float]:
    """Statically-known iteration count for MXL501.

    ``for _ in range(<const>...)`` -> the exact count;
    ``while True`` with no ``break`` -> inf;
    anything else (data loaders, dynamic bounds) -> None (unknown —
    never fires, keeping the pass quiet on short smoke loops whose
    bound we cannot see).
    """
    if isinstance(loop, ast.While):
        if isinstance(loop.test, ast.Constant) and loop.test.value:
            if any(isinstance(n, ast.Break) for n in ast.walk(loop)):
                return None
            return float("inf")
        return None
    it = loop.iter if isinstance(loop, ast.For) else None
    if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) and \
            it.func.id == "range" and not it.keywords and \
            all(isinstance(a, ast.Constant) and
                isinstance(a.value, int) for a in it.args):
        try:
            return float(len(range(*(a.value for a in it.args))))
        except (TypeError, ValueError):
            return None
    return None


def _step_output_names(loop) -> Set[str]:
    """Names the loop binds from a ``.step()``/``.step_multi()`` call
    (the MXL708 receivers); gym-convention ``env.step()`` is exempt."""
    names: Set[str] = set()
    for sub in ast.walk(loop):
        if not (isinstance(sub, ast.Assign) and
                isinstance(sub.value, ast.Call)):
            continue
        f = sub.value.func
        if not (isinstance(f, ast.Attribute) and
                f.attr in ("step", "step_multi")):
            continue
        chain = _attr_chain(f)
        if len(chain) >= 2 and chain[-2] in ("env", "environment"):
            continue
        for t in sub.targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    names.add(n.id)
    return names


def _jit_bindings(tree) -> dict:
    """``{name: has_donate}`` for every module-visible binding of a
    jit-compiled callable: ``f = jax.jit(fn, ...)`` assignments and
    ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorated defs — the
    MXL707 input."""
    out: dict = {}

    def _jit_call_donates(call: ast.Call):
        """(is_jit, has_donate) for a Call node."""
        chain = _attr_chain(call.func)
        if chain and chain[-1] == "jit":
            return True, any(kw.arg in ("donate_argnums",
                                        "donate_argnames")
                             for kw in call.keywords)
        if chain and chain[-1] == "partial" and call.args:
            inner = _attr_chain(call.args[0])
            if inner and inner[-1] == "jit":
                return True, any(kw.arg in ("donate_argnums",
                                            "donate_argnames")
                                 for kw in call.keywords)
        return False, False

    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            is_jit, donates = _jit_call_donates(n.value)
            if is_jit:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = donates
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in n.decorator_list:
                if isinstance(dec, ast.Call):
                    is_jit, donates = _jit_call_donates(dec)
                else:
                    chain = _attr_chain(dec)
                    is_jit, donates = (bool(chain) and
                                       chain[-1] == "jit"), False
                if is_jit:
                    out[n.name] = donates
                    break
    return out


def _loop_varying_names(loop) -> Set[str]:
    """Names the loop changes: induction targets + assignment targets in
    the body (these are the candidates for per-step attr values)."""
    names: Set[str] = set()

    def targets_of(t):
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                names.add(n.id)

    if isinstance(loop, ast.For):
        targets_of(loop.target)
    for sub in ast.walk(loop):
        if isinstance(sub, (ast.Assign, ast.AnnAssign)):
            tgts = sub.targets if isinstance(sub, ast.Assign) \
                else [sub.target]
            for t in tgts:
                targets_of(t)
        elif isinstance(sub, ast.AugAssign):
            targets_of(sub.target)
    return names


def _get_op(opname: str):
    try:
        from ..ops.registry import get_op
        return get_op(opname)
    except Exception:
        return None


class _SourceVisitor(ast.NodeVisitor):
    def __init__(self, filename: str, uses_step_compilation=False,
                 uses_checkpointing=False, uses_serving=False,
                 jit_fns=None):
        self.filename = filename
        self.findings: List[Finding] = []
        self._loops: List[dict] = []       # {training, varying, per_op}
        self._hybrid_depth = 0
        self._uses_step_compilation = uses_step_compilation
        self._uses_checkpointing = uses_checkpointing
        self._uses_serving = uses_serving
        #: module-visible jit bindings for MXL707: name -> has_donate
        self._jit_fns = jit_fns or {}

    # -- helpers ---------------------------------------------------------
    def _loc(self, node) -> str:
        return f"{self.filename}:{node.lineno}"

    def _in_training_loop(self) -> bool:
        return any(l["training"] for l in self._loops)

    def _step_outs(self) -> Set[str]:
        out: Set[str] = set()
        for l in self._loops:
            out |= l["step_outs"]
        return out

    def _is_step_output(self, node) -> bool:
        """Does this expression reference a name the enclosing loop
        nest bound from a ``.step()``/``.step_multi()`` call?"""
        outs = self._step_outs()
        if not outs:
            return False
        return any(isinstance(n, ast.Name) and n.id in outs
                   for n in ast.walk(node))

    def _varying(self) -> Set[str]:
        out: Set[str] = set()
        for l in self._loops:
            out |= l["varying"]
        return out

    # -- structure -------------------------------------------------------
    def _visit_loop(self, node):
        per_op = False
        if not self._uses_step_compilation and \
                not any(l["per_op"] for l in self._loops) and \
                _per_op_step_loop(node):
            per_op = True   # flag the OUTERMOST qualifying loop only
            self.findings.append(Finding(
                "MXL304", "training loop runs record()+backward()+"
                "step() per-op: a hybridize-eligible block here pays "
                "one dispatch per op each step; Trainer.compile_step "
                "collapses the whole step (and step_multi(K) bulks K "
                "steps) into ONE dispatch — see docs/compiled_step.md",
                self._loc(node)))
        induction: Set[str] = set()
        range_loop = False
        if isinstance(node, ast.For):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    induction.add(n.id)
            it = node.iter
            range_loop = (isinstance(it, ast.Call) and
                          isinstance(it.func, ast.Name) and
                          it.func.id == "range")
        self._loops.append({"training": _training_markers(node),
                            "varying": _loop_varying_names(node),
                            "per_op": per_op,
                            "count": _loop_trip_count(node),
                            "ckpt_fired": False,
                            "serving_fired": False,
                            "induction": induction,
                            "range_loop": range_loop,
                            "step_outs": _step_output_names(node)})
        self.generic_visit(node)
        self._loops.pop()

    visit_For = _visit_loop
    visit_While = _visit_loop

    def visit_FunctionDef(self, node):
        if node.name == "hybrid_forward":
            self._hybrid_depth += 1
            # a fresh function body is not part of the enclosing loop
            saved, self._loops = self._loops, []
            self.generic_visit(node)
            self._loops = saved
            self._hybrid_depth -= 1
        else:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- assignments -----------------------------------------------------
    def visit_Assign(self, node):
        # MXL707 (mxsan's static donation-coverage audit): a loop
        # rebinds a variable from a jit'd callee that takes the SAME
        # variable — dead after the call — but the jit construction
        # has no donate_argnums: the buffer is double-buffered in HBM
        # (>=64MiB of params there is exactly the waste MXL308/309
        # observe at runtime)
        if self._loops and isinstance(node.value, ast.Call) and \
                isinstance(node.value.func, ast.Name) and \
                self._jit_fns.get(node.value.func.id) is False:
            targets = {n.id for t in node.targets
                       for n in ast.walk(t)
                       if isinstance(n, ast.Name)}
            args = {a.id for a in node.value.args
                    if isinstance(a, ast.Name)}
            hit = sorted(targets & args)
            if hit:
                fname = node.value.func.id
                self.findings.append(Finding(
                    "MXL707",
                    f"{fname}(...) rebinds {', '.join(hit)} from its "
                    "own argument — the input is dead after the call — "
                    f"but the jax.jit binding of {fname!r} has no "
                    "donate_argnums: a >=64MiB buffer there is held "
                    "old AND new in HBM; donate the rebound argument "
                    "(docs/static_analysis.md, 'The sanitizer')",
                    self._loc(node)))
        self.generic_visit(node)

    # -- calls -----------------------------------------------------------
    def visit_Call(self, node):
        sync = _is_sync_call(node)
        if sync is not None:
            if self._hybrid_depth:
                self.findings.append(Finding(
                    "MXL302", f"{sync} inside hybrid_forward: breaks or "
                    "retraces the hybridized graph; compute on-device and "
                    "sync outside the block", self._loc(node)))
            elif self._in_training_loop():
                if _names_loss(node.func.value):
                    # MXL311 specializes MXL301: the receiver is the
                    # loss/metric itself, and the health plane already
                    # carries that signal out of the compiled step
                    self.findings.append(Finding(
                        "MXL311", f"{sync} reads the loss/metric to "
                        "the host EVERY step: a per-step device sync, "
                        "and redundant — the training-health plane "
                        "computes loss/grad-norm/nonfinite stats "
                        "inside the compiled step and samples them "
                        "every MXTPU_HEALTH_EVERY steps "
                        "(telemetry.health, docs/observability.md); "
                        "drop the read or consume the sampled plane",
                        self._loc(node)))
                elif self._is_step_output(node.func.value):
                    self.findings.append(Finding(
                        "MXL708", f"{sync} on a step output inside "
                        "the hot loop: a device round-trip per "
                        "iteration; keep the output on-device (or "
                        "consume the sampled health plane) and sync "
                        "once per log interval", self._loc(node)))
                else:
                    self.findings.append(Finding(
                        "MXL301", f"{sync} inside a training loop "
                        "forces a host sync every step; accumulate "
                        "on-device and sync once per epoch/log "
                        "interval", self._loc(node)))
        elif self._in_training_loop():
            # cast-syncs are only flagged in training loops; inside
            # hybrid_forward int()/float() legitimately fold shapes and
            # would be all noise
            cast = _is_cast_sync(node)
            if cast is not None:
                if _names_loss(node.args[0]):
                    self.findings.append(Finding(
                        "MXL311", f"{cast} converts the loss/metric "
                        "to a host scalar EVERY step: a per-step "
                        "device sync, and redundant — the training-"
                        "health plane computes loss/grad-norm/"
                        "nonfinite stats inside the compiled step and "
                        "samples them every MXTPU_HEALTH_EVERY steps "
                        "(telemetry.health, docs/observability.md)",
                        self._loc(node)))
                elif self._is_step_output(node.args[0]):
                    self.findings.append(Finding(
                        "MXL708", f"{cast} on a step output inside "
                        "the hot loop: an implicit device sync per "
                        "iteration (host scalar conversion); keep it "
                        "on-device and sync once per log interval",
                        self._loc(node)))
                else:
                    self.findings.append(Finding(
                        "MXL301", f"{cast} on an array inside a "
                        "training loop is an implicit device sync "
                        "(host scalar conversion)", self._loc(node)))
            else:
                # np.asarray(step_output): a full host materialization
                # the other sync detectors do not cover — mxsan's
                # MXL708 (loss-named receivers stay MXL311's beat)
                chain = _attr_chain(node.func)
                if len(chain) >= 2 and chain[-1] == "asarray" and \
                        chain[-2] in ("np", "numpy") and node.args and \
                        self._is_step_output(node.args[0]) and \
                        not _names_loss(node.args[0]):
                    self.findings.append(Finding(
                        "MXL708", "np.asarray(...) on a step output "
                        "inside the hot loop: a full host "
                        "materialization per iteration; keep the "
                        "output on-device and sync once per log "
                        "interval", self._loc(node)))

        if self._loops:
            self._check_per_step_attrs(node)
            self._check_unckpt_loop(node)
            self._check_unserved_loop(node)
        self.generic_visit(node)

    def _check_unserved_loop(self, node: ast.Call):
        """MXL601: a model-zoo ``prefill``/``decode_step``/``generate``
        call inside a loop — the per-request serving shape — in a
        module that never touches the serving plane (``Server`` /
        bucketed warm path).  Each request pays a fresh prefill, T
        per-op decode dispatches, and a NEW compile per unseen prompt
        length; ``serving.Server`` amortizes all three into fixed
        bucket programs (the serving sibling of MXL304).  A model's
        own ``self.<method>`` implementation is exempt — generate()'s
        internal decode loop is the implementation, not a request
        loop."""
        if self._uses_serving:
            return
        f = node.func
        if not (isinstance(f, ast.Attribute) and
                f.attr in _SERVING_CALLS):
            return
        chain = _attr_chain(f)
        if chain and chain[0] == "self":
            return
        if chain and any(chain[0] in l["induction"]
                         for l in self._loops):
            # the receiver IS the thing being iterated (`for layer in
            # self.layers: layer.prefill(...)`) — submodule plumbing
            # inside a model implementation, not a request loop
            return
        if f.attr in ("prefill", "decode_step") and \
                self._loops[-1]["range_loop"]:
            # `for i in range(S): net.decode_step(tok, caches, i)` is
            # the incremental-decode IMPLEMENTATION shape — one
            # sequence, stepping positions — not a request loop
            # (requests iterate a collection of prompts; whole-request
            # calls like generate() stay flagged in any loop)
            return
        if any(l["serving_fired"] for l in self._loops):
            return          # one finding per loop nest
        self._loops[0]["serving_fired"] = True
        self.findings.append(Finding(
            "MXL601", f".{f.attr}() inside a request loop without the "
            "serving plane in scope: every request pays its own "
            "prefill + per-op decode dispatches, and each UNSEEN "
            "prompt length compiles fresh programs; serving.Server "
            "batches requests into fixed (slots, prompt_len) buckets "
            "— one compiled prefill + one compiled decode program "
            "each, zero steady-state retraces, warm-startable via "
            "save_signature/warm_start — see docs/serving.md",
            self._loc(node)))

    def _check_unckpt_loop(self, node: ast.Call):
        """MXL501: this step call's loop nest runs >= the threshold
        (statically known) and the module never references a
        checkpointing surface."""
        if self._uses_checkpointing:
            return
        f = node.func
        if not (isinstance(f, ast.Attribute) and
                f.attr in ("step", "step_multi")):
            return
        chain = _attr_chain(f)
        if len(chain) >= 2 and chain[-2] in ("env", "environment"):
            return          # gym-convention env.step(): not a trainer
        if any(l["ckpt_fired"] for l in self._loops):
            return          # one finding per loop nest
        total = 1.0
        known = False
        for l in self._loops:
            if l["count"] is not None:
                total *= l["count"]
                known = True
        if f.attr == "step_multi":
            # a constant repeat=K (the bulked-step API) multiplies
            # the dispatched step count
            for kw in node.keywords:
                if kw.arg == "repeat" and \
                        isinstance(kw.value, ast.Constant) and \
                        isinstance(kw.value.value, int):
                    total *= max(1, kw.value.value)
        if not known or total < _CKPT_LOOP_MIN_STEPS:
            return
        self._loops[0]["ckpt_fired"] = True
        n = "unbounded" if total == float("inf") else f"~{int(total)}"
        self.findings.append(Finding(
            "MXL501", f"training loop dispatches .{f.attr}() {n} "
            "times with no CheckpointManager in scope: one preemption "
            "or post-donation dispatch failure loses the whole run; "
            "wrap the loop with elastic.CheckpointManager (save "
            "periodically, recover(manager) on poison) — see "
            "docs/elasticity.md", self._loc(node)))

    def _check_per_step_attrs(self, node: ast.Call):
        chain = _attr_chain(node.func)
        if len(chain) < 2 or chain[-2] not in _OP_NAMESPACES:
            return
        op = _get_op(chain[-1])
        if op is None or not node.keywords:
            return
        varying = self._varying()
        if not varying:
            return
        static_attrs = set(op.attr_names) - set(op.scalar_attrs)
        for kw in node.keywords:
            if kw.arg is None or kw.arg not in static_attrs:
                continue
            if isinstance(kw.value, ast.Constant):
                continue
            used = {n.id for n in ast.walk(kw.value)
                    if isinstance(n, ast.Name)}
            hit = used & varying
            if hit:
                self.findings.append(Finding(
                    "MXL303", f"{chain[-1]}(..., {kw.arg}=...) passes a "
                    f"per-step value ({', '.join(sorted(hit))}) as a "
                    "STATIC attr: the jit cache keys on it, recompiling "
                    "every iteration; declare it in scalar_attrs or hoist "
                    "it out of the loop", self._loc(node)))


def _apply_suppressions(findings: List[Finding], text: str) -> List[Finding]:
    lines = text.splitlines()
    out = []
    for f in findings:
        try:
            lineno = int(f.location.rsplit(":", 1)[1])
            line = lines[lineno - 1]
        except (IndexError, ValueError):
            out.append(f)
            continue
        m = _DISABLE_RE.search(line)
        if m is None:
            out.append(f)
            continue
        if m.group(1) is None:
            continue  # bare disable: every rule
        ids = {s.strip() for s in m.group(1).split(",")}
        if f.rule not in ids:
            out.append(f)
    return out


def analyze_source(text: str, filename: str = "<string>") -> List[Finding]:
    """Lint one Python source text."""
    try:
        tree = ast.parse(text, filename=filename)
    except SyntaxError:
        # not our diagnostic to own — report nothing; CI's own syntax
        # gates catch it
        return []
    v = _SourceVisitor(
        filename,
        uses_step_compilation=_module_uses_step_compilation(tree),
        uses_checkpointing=_module_uses_checkpointing(tree),
        uses_serving=_module_uses_serving(tree),
        jit_fns=_jit_bindings(tree))
    v.visit(tree)
    return _apply_suppressions(v.findings, text)


def analyze_file(path: str) -> List[Finding]:
    with open(path, encoding="utf-8", errors="replace") as f:
        return analyze_source(f.read(), filename=path)


def analyze_paths(paths, exts=(".py",)) -> List[Finding]:
    """Walk files/directories; lint every matching source file."""
    findings: List[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in sorted(dirs)
                           if d not in ("__pycache__", ".git")]
                for fn in sorted(files):
                    if fn.endswith(tuple(exts)):
                        findings.extend(
                            analyze_file(os.path.join(root, fn)))
        elif p.endswith(".json"):
            from .graph_passes import analyze_graph_json
            with open(p, encoding="utf-8") as f:
                findings.extend(analyze_graph_json(f.read(), name=p))
        else:
            findings.extend(analyze_file(p))
    return findings
