"""Finding/rule model shared by every mxlint pass family.

A finding is one diagnostic: a stable rule ID (``MXL...``), a severity,
a human message, and an anchor — ``file:line`` for source passes, a
``graph:`` node path for graph passes, ``op:`` / ``cache:`` for the
registry and runtime passes.  Severities gate the CLI exit code: only
``error`` findings fail a build; ``warning``/``info`` inform.
"""
from __future__ import annotations

from typing import Iterable, List, Optional

__all__ = ["Severity", "Finding", "RULES", "rule_severity",
           "filter_findings", "format_findings", "rules_markdown"]


class Severity:
    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    _ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

    @classmethod
    def at_least(cls, sev: str, floor: str) -> bool:
        return cls._ORDER[sev] <= cls._ORDER[floor]


#: rule ID -> (default severity, one-line title).  IDs are stable API:
#: docs/static_analysis.md documents each one; suppression comments and
#: CI configs reference them by ID.
RULES = {
    # -- graph passes (MXL1xx) ------------------------------------------
    "MXL101": (Severity.ERROR, "cycle in symbol graph"),
    "MXL102": (Severity.ERROR, "duplicate node name"),
    "MXL103": (Severity.WARNING, "dead node unreachable from any head"),
    "MXL104": (Severity.WARNING, "unused variable input"),
    "MXL105": (Severity.ERROR, "shape/dtype contract violation"),
    "MXL106": (Severity.ERROR, "unknown operator"),
    "MXL107": (Severity.ERROR, "node arity mismatch vs op registry"),
    "MXL108": (Severity.WARNING, "unknown static attr on node"),
    "MXL109": (Severity.INFO, "input shape unknown; node not validated"),
    "MXL110": (Severity.ERROR, "malformed graph JSON"),
    # -- registry passes (MXL2xx) ---------------------------------------
    "MXL201": (Severity.ERROR,
               "fcompute arity inconsistent with num_inputs+scalar_attrs"),
    "MXL202": (Severity.ERROR,
               "scalar_attrs do not name the trailing fcompute params"),
    "MXL203": (Severity.ERROR, "scalar_ref_input out of bounds"),
    "MXL204": (Severity.ERROR, "num_outputs inconsistent with fcompute"),
    "MXL205": (Severity.ERROR, "nd/sym namespace exposure asymmetric"),
    "MXL206": (Severity.WARNING,
               "unhashable default attr (jit-cache key degradation)"),
    "MXL207": (Severity.ERROR, "alias target not registered"),
    # -- source passes (MXL3xx) -----------------------------------------
    "MXL301": (Severity.WARNING, "device sync inside training loop"),
    "MXL302": (Severity.WARNING, "device sync inside hybrid_forward"),
    "MXL303": (Severity.WARNING,
               "per-step-varying static attr (recompile per value)"),
    "MXL304": (Severity.WARNING,
               "per-op training loop without step compilation"),
    "MXL305": (Severity.WARNING,
               "CompiledStep silently fell back to the eager path"),
    "MXL306": (Severity.WARNING,
               "retrace observed after warm-up (attributed cause)"),
    "MXL307": (Severity.WARNING,
               "prefetch stall ratio above threshold (input-bound)"),
    "MXL308": (Severity.WARNING,
               "large updated buffer not in the donate tuple "
               "(double-buffered in HBM)"),
    "MXL309": (Severity.WARNING,
               "large tensor fully replicated across a multi-device "
               "mesh"),
    "MXL310": (Severity.WARNING,
               "MXTPU_ZERO_STAGE>=1 set but a dp>1 trainer's optimizer "
               "state is fully replicated (misconfigured plan silently "
               "burning HBM)"),
    "MXL311": (Severity.WARNING,
               "per-step host scalar read of the loss/metric in a "
               "training loop (use the sampled health plane)"),
    "MXL312": (Severity.WARNING,
               "training-health anomalies recorded in this process "
               "(divergence risk; runtime sibling of MXL311)"),
    "MXL313": (Severity.WARNING,
               "sharding-plan coverage hazard: a trainable param no "
               "rule matches (silent replication), a rule shadowed by "
               "an earlier regex, a big tensor the resolved plan "
               "fully replicates on a multi-device mesh, or a rule "
               "demoted because a sharded dim does not divide its "
               "axis fan-out"),
    # -- runtime passes (MXL4xx) ----------------------------------------
    "MXL401": (Severity.WARNING, "jit-cache key blowup for one op"),
    "MXL402": (Severity.ERROR,
               "corrupt persistent compile-cache entry"),
    # -- elasticity passes (MXL5xx) -------------------------------------
    "MXL501": (Severity.WARNING,
               "long training loop with no CheckpointManager in scope "
               "(a failure loses the whole run)"),
    "MXL502": (Severity.ERROR,
               "corrupt or torn elastic checkpoint"),
    "MXL503": (Severity.WARNING,
               "live resize broke its contract (post-swap fresh "
               "compile, or the drain committed an older step than "
               "the trainer had)"),
    "MXL504": (Severity.WARNING,
               "guardian-plane incident without a matching recovery "
               "(an unrecovered hang_suspected, a preemption that "
               "committed nothing) or a chaos-soak artifact with "
               "violated invariants"),
    "MXL505": (Severity.WARNING,
               "silent-corruption incident left open: a "
               "corruption_suspected with no later rollback/"
               "quarantine/clean resolution, or a scrub-found-corrupt "
               "checkpoint still standing as a restore target (that "
               "one at ERROR severity)"),
    # -- serving passes (MXL6xx) ----------------------------------------
    "MXL601": (Severity.WARNING,
               "per-request prefill/decode loop without the serving "
               "plane (per-request compile hazard; runtime form: a "
               "serving bucket kept compiling in steady state)"),
    # -- sanitizer passes (MXL7xx: mxsan, docs/static_analysis.md
    # "The sanitizer") ---------------------------------------------------
    "MXL701": (Severity.ERROR,
               "use-after-donate: a buffer a donated dispatch already "
               "consumed was handed to another dispatch (the shadow "
               "lifetime machine attributes the consuming op/owner)"),
    "MXL702": (Severity.ERROR,
               "double donation: the same buffer sits at two donate "
               "indices of one dispatch (XLA may alias both outputs "
               "onto one allocation — silent corruption)"),
    "MXL703": (Severity.WARNING,
               "a poisoned owner was stepped without recover(): the "
               "donated state is gone and the step can only fail"),
    "MXL704": (Severity.WARNING,
               "live-bytes leak: the tracked live-buffer census ended "
               "above its warmed baseline (buffers pinned past their "
               "step; see the sanitizer's leak report)"),
    "MXL705": (Severity.ERROR,
               "lock-order cycle: the instrumented module locks were "
               "acquired in inconsistent order on different threads "
               "(potential deadlock; the finding names the cycle)"),
    "MXL706": (Severity.WARNING,
               "a module lock was held across a blocking device "
               "dispatch (stall hazard: every other thread wanting "
               "the lock waits out the device)"),
    "MXL707": (Severity.WARNING,
               "dead-after-call input not donated: a jit-compiled "
               "step rebinds its own argument from the result (the "
               "input is dead after the call) but the jit has no "
               "donate_argnums — a >=64MiB buffer there is "
               "double-buffered in HBM (static twin of MXL308/309)"),
    "MXL708": (Severity.WARNING,
               "host sync on a step output inside a hot loop "
               "(.item()/float()/np.asarray() on what step() "
               "returned): a device round-trip per iteration"),
    # -- wire passes (MXL8xx: mxwire, docs/static_analysis.md
    # "The wire auditor") -------------------------------------------------
    "MXL801": (Severity.ERROR,
               "wire leg wider than the plan's declared precision: a "
               "collective's on-wire dtype is wider than the "
               "ShardingPlan.precision entry for that leg kind (the "
               "silent fp32-widening class — a quantized leg paying "
               "full-width bytes)"),
    "MXL802": (Severity.ERROR,
               "all-reduce surviving on a ZeRO-2 grad leg: the "
               "stage-2 wire contract requires reduce-scatter + "
               "all-gather, but a full psum still moves the whole "
               "gradient over the dp axis"),
    "MXL803": (Severity.WARNING,
               "ungated observability collective: a stats/fingerprint "
               "leg executes outside the health plane's lax.cond(due) "
               "sampling gate in a variant the spec claims is sampled "
               "(paying unsampled wire cost every step)"),
    "MXL804": (Severity.WARNING,
               "static bytes-on-wire diverges >10% from the memory "
               "observatory's runtime accounting for the same step "
               "variant (either the static wire model or the runtime "
               "counter is lying)"),
}


def rule_severity(rule: str) -> str:
    return RULES[rule][0]


class Finding:
    """One diagnostic."""

    __slots__ = ("rule", "severity", "message", "location")

    def __init__(self, rule: str, message: str,
                 location: str = "", severity: Optional[str] = None):
        if rule not in RULES:
            raise KeyError(f"unknown mxlint rule {rule!r}")
        self.rule = rule
        self.severity = severity or RULES[rule][0]
        self.message = message
        self.location = location

    def __repr__(self):
        return (f"Finding({self.rule}, {self.severity}, "
                f"{self.location!r}, {self.message!r})")

    def format(self) -> str:
        loc = f"{self.location}: " if self.location else ""
        return f"{loc}{self.severity.upper()} {self.rule}: {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "message": self.message, "location": self.location}


#: rule-ID prefix -> family name, for the generated docs index
_FAMILIES = {
    "MXL1": "graph passes",
    "MXL2": "registry passes",
    "MXL3": "source passes",
    "MXL4": "runtime passes",
    "MXL5": "elasticity passes",
    "MXL6": "serving passes",
    "MXL7": "sanitizer (mxsan)",
    "MXL8": "wire auditor (mxwire)",
}


def rules_markdown() -> str:
    """The full MXL rule index as a markdown table, generated from
    :data:`RULES` — the docs/static_analysis.md "Rule index" section is
    this function's output, and a tier-1 drift test asserts every
    registered rule id has a docs row (a new rule cannot land
    undocumented)."""
    lines = ["| rule | family | severity | title |",
             "|---|---|---|---|"]
    for rule in sorted(RULES):
        sev, title = RULES[rule]
        fam = _FAMILIES.get(rule[:4], "?")
        lines.append(f"| {rule} | {fam} | {sev} | "
                     f"{' '.join(title.split())} |")
    return "\n".join(lines) + "\n"


def filter_findings(findings: Iterable[Finding],
                    disable: Iterable[str] = ()) -> List[Finding]:
    disable = set(disable)
    return [f for f in findings if f.rule not in disable]


def format_findings(findings: Iterable[Finding]) -> str:
    return "\n".join(f.format() for f in findings)
