"""Finding/rule model shared by every mxlint pass family.

A finding is one diagnostic: a stable rule ID (``MXL...``), a severity,
a human message, and an anchor — ``file:line`` for source passes, a
``graph:`` node path for graph passes, ``op:`` / ``cache:`` for the
registry and runtime passes.  Severities gate the CLI exit code: only
``error`` findings fail a build; ``warning``/``info`` inform.
"""
from __future__ import annotations

from typing import Iterable, List, Optional

__all__ = ["Severity", "Finding", "RULES", "rule_severity",
           "filter_findings", "format_findings"]


class Severity:
    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    _ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

    @classmethod
    def at_least(cls, sev: str, floor: str) -> bool:
        return cls._ORDER[sev] <= cls._ORDER[floor]


#: rule ID -> (default severity, one-line title).  IDs are stable API:
#: docs/static_analysis.md documents each one; suppression comments and
#: CI configs reference them by ID.
RULES = {
    # -- graph passes (MXL1xx) ------------------------------------------
    "MXL101": (Severity.ERROR, "cycle in symbol graph"),
    "MXL102": (Severity.ERROR, "duplicate node name"),
    "MXL103": (Severity.WARNING, "dead node unreachable from any head"),
    "MXL104": (Severity.WARNING, "unused variable input"),
    "MXL105": (Severity.ERROR, "shape/dtype contract violation"),
    "MXL106": (Severity.ERROR, "unknown operator"),
    "MXL107": (Severity.ERROR, "node arity mismatch vs op registry"),
    "MXL108": (Severity.WARNING, "unknown static attr on node"),
    "MXL109": (Severity.INFO, "input shape unknown; node not validated"),
    "MXL110": (Severity.ERROR, "malformed graph JSON"),
    # -- registry passes (MXL2xx) ---------------------------------------
    "MXL201": (Severity.ERROR,
               "fcompute arity inconsistent with num_inputs+scalar_attrs"),
    "MXL202": (Severity.ERROR,
               "scalar_attrs do not name the trailing fcompute params"),
    "MXL203": (Severity.ERROR, "scalar_ref_input out of bounds"),
    "MXL204": (Severity.ERROR, "num_outputs inconsistent with fcompute"),
    "MXL205": (Severity.ERROR, "nd/sym namespace exposure asymmetric"),
    "MXL206": (Severity.WARNING,
               "unhashable default attr (jit-cache key degradation)"),
    "MXL207": (Severity.ERROR, "alias target not registered"),
    # -- source passes (MXL3xx) -----------------------------------------
    "MXL301": (Severity.WARNING, "device sync inside training loop"),
    "MXL302": (Severity.WARNING, "device sync inside hybrid_forward"),
    "MXL303": (Severity.WARNING,
               "per-step-varying static attr (recompile per value)"),
    "MXL304": (Severity.WARNING,
               "per-op training loop without step compilation"),
    "MXL305": (Severity.WARNING,
               "CompiledStep silently fell back to the eager path"),
    "MXL306": (Severity.WARNING,
               "retrace observed after warm-up (attributed cause)"),
    "MXL307": (Severity.WARNING,
               "prefetch stall ratio above threshold (input-bound)"),
    "MXL308": (Severity.WARNING,
               "large updated buffer not in the donate tuple "
               "(double-buffered in HBM)"),
    "MXL309": (Severity.WARNING,
               "large tensor fully replicated across a multi-device "
               "mesh"),
    "MXL310": (Severity.WARNING,
               "MXTPU_ZERO_STAGE>=1 set but a dp>1 trainer's optimizer "
               "state is fully replicated (misconfigured plan silently "
               "burning HBM)"),
    "MXL311": (Severity.WARNING,
               "per-step host scalar read of the loss/metric in a "
               "training loop (use the sampled health plane)"),
    "MXL312": (Severity.WARNING,
               "training-health anomalies recorded in this process "
               "(divergence risk; runtime sibling of MXL311)"),
    "MXL313": (Severity.WARNING,
               "sharding-plan coverage hazard: a trainable param no "
               "rule matches (silent replication), a rule shadowed by "
               "an earlier regex, a big tensor the resolved plan "
               "fully replicates on a multi-device mesh, or a rule "
               "demoted because a sharded dim does not divide its "
               "axis fan-out"),
    # -- runtime passes (MXL4xx) ----------------------------------------
    "MXL401": (Severity.WARNING, "jit-cache key blowup for one op"),
    "MXL402": (Severity.ERROR,
               "corrupt persistent compile-cache entry"),
    # -- elasticity passes (MXL5xx) -------------------------------------
    "MXL501": (Severity.WARNING,
               "long training loop with no CheckpointManager in scope "
               "(a failure loses the whole run)"),
    "MXL502": (Severity.ERROR,
               "corrupt or torn elastic checkpoint"),
    "MXL503": (Severity.WARNING,
               "live resize broke its contract (post-swap fresh "
               "compile, or the drain committed an older step than "
               "the trainer had)"),
    "MXL504": (Severity.WARNING,
               "guardian-plane incident without a matching recovery "
               "(an unrecovered hang_suspected, a preemption that "
               "committed nothing) or a chaos-soak artifact with "
               "violated invariants"),
    "MXL505": (Severity.WARNING,
               "silent-corruption incident left open: a "
               "corruption_suspected with no later rollback/"
               "quarantine/clean resolution, or a scrub-found-corrupt "
               "checkpoint still standing as a restore target (that "
               "one at ERROR severity)"),
    # -- serving passes (MXL6xx) ----------------------------------------
    "MXL601": (Severity.WARNING,
               "per-request prefill/decode loop without the serving "
               "plane (per-request compile hazard; runtime form: a "
               "serving bucket kept compiling in steady state)"),
}


def rule_severity(rule: str) -> str:
    return RULES[rule][0]


class Finding:
    """One diagnostic."""

    __slots__ = ("rule", "severity", "message", "location")

    def __init__(self, rule: str, message: str,
                 location: str = "", severity: Optional[str] = None):
        if rule not in RULES:
            raise KeyError(f"unknown mxlint rule {rule!r}")
        self.rule = rule
        self.severity = severity or RULES[rule][0]
        self.message = message
        self.location = location

    def __repr__(self):
        return (f"Finding({self.rule}, {self.severity}, "
                f"{self.location!r}, {self.message!r})")

    def format(self) -> str:
        loc = f"{self.location}: " if self.location else ""
        return f"{loc}{self.severity.upper()} {self.rule}: {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "message": self.message, "location": self.location}


def filter_findings(findings: Iterable[Finding],
                    disable: Iterable[str] = ()) -> List[Finding]:
    disable = set(disable)
    return [f for f in findings if f.rule not in disable]


def format_findings(findings: Iterable[Finding]) -> str:
    return "\n".join(f.format() for f in findings)
