"""Lint corpus: the shipped graphs mxlint gates CI against.

Two sources:

* hand-built symbols exercising the classic layer mix (MLP; conv +
  BatchNorm aux-state graph), fast enough for every CI run;
* traced model symbols — gluon model-zoo vision nets and the
  ``mxnet_tpu.models`` families — obtained through the same
  ``block(sym.var(...))`` seam ``HybridBlock.export`` uses, so the linted
  graph is byte-for-byte the graph a user would serialize.

Every entry is ``(name, Symbol, input_shapes)`` where the shapes feed the
MXL105 contract validator.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

__all__ = ["builtin_symbols", "traced_model_symbols", "model_corpus",
           "wire_defect_corpus"]


def builtin_symbols() -> List[Tuple[str, object, Dict[str, tuple]]]:
    from .. import symbol as sym

    data = sym.var("data")
    h = sym.FullyConnected(data, sym.var("fc1_weight"),
                           sym.var("fc1_bias"), num_hidden=64, name="fc1")
    h = sym.Activation(h, act_type="relu", name="relu1")
    h = sym.FullyConnected(h, sym.var("fc2_weight"), sym.var("fc2_bias"),
                           num_hidden=10, name="fc2")
    mlp = sym.softmax(h, name="softmax")

    x = sym.var("img")
    c = sym.Convolution(x, sym.var("conv1_weight"), sym.var("conv1_bias"),
                        kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name="conv1")
    bn = sym.BatchNorm(c, sym.var("bn1_gamma"), sym.var("bn1_beta"),
                       sym.var("bn1_mean"), sym.var("bn1_var"),
                       name="bn1")
    a = sym.Activation(bn, act_type="relu", name="relu_c")
    p = sym.Pooling(a, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="pool1")
    f = sym.flatten(p, name="flat")
    out = sym.FullyConnected(f, sym.var("fco_weight"),
                             sym.var("fco_bias"), num_hidden=10,
                             name="fc_out")
    convnet = sym.softmax(out, name="prob")

    grouped = sym.Group([mlp, sym.FullyConnected(
        data, sym.var("aux_weight"), sym.var("aux_bias"),
        num_hidden=4, name="aux_head")])

    return [("mlp", mlp, {"data": (2, 784)}),
            ("convnet_bn", convnet, {"img": (2, 3, 8, 8)}),
            ("mlp_group", grouped, {"data": (2, 784)})]


def _trace(net, *input_shapes, names=None) -> Tuple[object, Dict]:
    """Initialize a HybridBlock and trace it to a Symbol (export seam)."""
    import mxnet_tpu as mx
    from .. import symbol as sym
    net.initialize(mx.init.Xavier())
    names = names or (["data"] if len(input_shapes) == 1 else
                      [f"data{i}" for i in range(len(input_shapes))])
    out = net(*[sym.var(n) for n in names])
    return out, dict(zip(names, input_shapes))


def traced_model_symbols(full: bool = False) \
        -> Iterator[Tuple[str, object, Dict[str, tuple]]]:
    """Traced symbols for the shipped model zoo.

    The default set keeps tier-1 CI fast; ``full=True`` adds more
    families (``tools/mxlint.py --models`` uses it).  The
    ``mxnet_tpu.models`` transformer families (BERT/Llama/NMT/SSD) read
    ``x.shape`` inside ``hybrid_forward`` — imperative-only, like the
    reference — so they have no Symbol form to lint; their graphs are
    covered imperatively by their own test files.
    """
    from ..gluon.model_zoo import get_model

    net = get_model("resnet18_v1", classes=10, thumbnail=True)
    yield ("zoo:resnet18_v1",) + _trace(net, (1, 3, 32, 32))

    if not full:
        return

    net = get_model("alexnet", classes=10)
    yield ("zoo:alexnet",) + _trace(net, (1, 3, 224, 224))

    net = get_model("mobilenet0.25", classes=10)
    yield ("zoo:mobilenet0.25",) + _trace(net, (1, 3, 224, 224))


def model_corpus(full: bool = False) \
        -> List[Tuple[str, object, Dict[str, tuple]]]:
    out = list(builtin_symbols())
    out.extend(traced_model_symbols(full=full))
    return out


def wire_defect_corpus() -> List[dict]:
    """Seeded wire defects + clean twins for the MXL8xx auditor.

    Each entry is everything :func:`..analysis.analyze_wire`'s explicit
    entry point needs — a closed jaxpr (small shard_map'd step bodies
    on the process dp=8 mesh, traced abstractly), the plan, and the
    registration kwargs — plus the expectation::

        {"name": ..., "rule": "MXL801", "clean": False,
         "jaxpr": <ClosedJaxpr>, "plan": <ShardingPlan|None>,
         "kwargs": {...}}

    The four defects (ISSUE 16 satellite): an fp32 grad leg under an
    ``int8`` plan declaration (MXL801), a full psum smuggled onto the
    ZeRO-2 grad leg (MXL802), an ungated fingerprint row in a sampled
    variant (MXL803), and a cooked observatory counter (MXL804); each
    twin repairs exactly the seeded defect.  Needs the 8-virtual-device
    CPU mesh (``tests/conftest.py`` sets it up; gate with
    ``needs_mesh(8)``).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .. import parallel
    from ..parallel._compat import shard_map
    from ..parallel.planner import ShardingPlan

    mesh = parallel.make_mesh({"dp": 8})
    N = 65536                       # global f4 grad: 8192 elems/device
    g_aval = jax.ShapeDtypeStruct((N,), jnp.float32)

    def _psum_grads(g):             # the dense wire: one full psum
        return jax.lax.psum(g, "dp")

    def _quantized_grads(g):        # int8 codes + an fp32 scale lane
        scale = jax.lax.pmax(jnp.max(jnp.abs(g)), "dp") / 127.0 + 1e-8
        codes = jnp.clip(jnp.round(g / scale), -127, 127) \
            .astype(jnp.int8)
        wide = jax.lax.psum(codes, "dp")        # int8 on the wire
        return wide.astype(jnp.float32) * scale

    def _stage2_grads(g):           # the ZeRO-2 contract shape
        part = jax.lax.psum_scatter(g, "dp", scatter_dimension=0,
                                    tiled=True)
        return jax.lax.all_gather(part, "dp", tiled=True)

    def _fingerprint(g):            # one u32 integrity row, UNGATED
        row = jnp.sum(g).astype(jnp.uint32)[None]
        return jax.lax.all_gather(row, "dp")

    def _step_ungated(g, due):
        del due                     # the seeded defect: gate ignored
        return g * 0.9, _fingerprint(g)

    def _step_gated(g, due):
        fp = jax.lax.cond(
            due, lambda: _fingerprint(g),
            lambda: jnp.zeros((8, 1), jnp.uint32))
        return g * 0.9, fp

    def _smap(f, n_in=1):
        specs = (P("dp"), P())[:n_in]
        outs = P() if n_in == 1 else (P("dp"), P())
        return shard_map(f, mesh, in_specs=specs, out_specs=outs,
                         check_vma=False)

    due = jax.ShapeDtypeStruct((), jnp.bool_)
    jx_psum = jax.make_jaxpr(_smap(_psum_grads))(g_aval)
    jx_quant = jax.make_jaxpr(_smap(_quantized_grads))(g_aval)
    jx_stage2 = jax.make_jaxpr(_smap(_stage2_grads))(g_aval)
    jx_ungated = jax.make_jaxpr(_smap(_step_ungated, 2))(g_aval, due)
    jx_gated = jax.make_jaxpr(_smap(_step_gated, 2))(g_aval, due)

    # static bytes the psum variant puts on the wire (the ring model):
    # per-device payload x 2(k-1)/k — what a truthful observatory
    # counter reports for the same program
    payload = (N // 8) * 4
    psum_wire = 2 * payload * 7 // 8

    int8_plan = ShardingPlan({"dp": 8}, precision={"dp_grad": "int8"})
    obs_kw = {"sampled": True, "obs_outputs": (-1,)}
    return [
        {"name": "fp32_widened_int8_leg", "rule": "MXL801",
         "clean": False, "jaxpr": jx_psum, "plan": int8_plan,
         "kwargs": {}},
        {"name": "quantized_leg_matches_plan", "rule": "MXL801",
         "clean": True, "jaxpr": jx_quant, "plan": int8_plan,
         "kwargs": {}},
        {"name": "psum_on_zero2_grad_leg", "rule": "MXL802",
         "clean": False, "jaxpr": jx_psum, "plan": None,
         "kwargs": {"zero_stage": 2}},
        {"name": "stage2_contract_shape", "rule": "MXL802",
         "clean": True, "jaxpr": jx_stage2, "plan": None,
         "kwargs": {"zero_stage": 2}},
        {"name": "ungated_fingerprint_row", "rule": "MXL803",
         "clean": False, "jaxpr": jx_ungated, "plan": None,
         "kwargs": dict(obs_kw)},
        {"name": "fingerprint_under_cond_gate", "rule": "MXL803",
         "clean": True, "jaxpr": jx_gated, "plan": None,
         "kwargs": dict(obs_kw)},
        {"name": "cooked_observatory_counter", "rule": "MXL804",
         "clean": False, "jaxpr": jx_psum, "plan": None,
         "kwargs": {"measured_wire_bytes": psum_wire * 2}},
        {"name": "reconciled_observatory_counter", "rule": "MXL804",
         "clean": True, "jaxpr": jx_psum, "plan": None,
         "kwargs": {"measured_wire_bytes": psum_wire}},
    ]
