"""Lint corpus: the shipped graphs mxlint gates CI against.

Two sources:

* hand-built symbols exercising the classic layer mix (MLP; conv +
  BatchNorm aux-state graph), fast enough for every CI run;
* traced model symbols — gluon model-zoo vision nets and the
  ``mxnet_tpu.models`` families — obtained through the same
  ``block(sym.var(...))`` seam ``HybridBlock.export`` uses, so the linted
  graph is byte-for-byte the graph a user would serialize.

Every entry is ``(name, Symbol, input_shapes)`` where the shapes feed the
MXL105 contract validator.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

__all__ = ["builtin_symbols", "traced_model_symbols", "model_corpus"]


def builtin_symbols() -> List[Tuple[str, object, Dict[str, tuple]]]:
    from .. import symbol as sym

    data = sym.var("data")
    h = sym.FullyConnected(data, sym.var("fc1_weight"),
                           sym.var("fc1_bias"), num_hidden=64, name="fc1")
    h = sym.Activation(h, act_type="relu", name="relu1")
    h = sym.FullyConnected(h, sym.var("fc2_weight"), sym.var("fc2_bias"),
                           num_hidden=10, name="fc2")
    mlp = sym.softmax(h, name="softmax")

    x = sym.var("img")
    c = sym.Convolution(x, sym.var("conv1_weight"), sym.var("conv1_bias"),
                        kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name="conv1")
    bn = sym.BatchNorm(c, sym.var("bn1_gamma"), sym.var("bn1_beta"),
                       sym.var("bn1_mean"), sym.var("bn1_var"),
                       name="bn1")
    a = sym.Activation(bn, act_type="relu", name="relu_c")
    p = sym.Pooling(a, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="pool1")
    f = sym.flatten(p, name="flat")
    out = sym.FullyConnected(f, sym.var("fco_weight"),
                             sym.var("fco_bias"), num_hidden=10,
                             name="fc_out")
    convnet = sym.softmax(out, name="prob")

    grouped = sym.Group([mlp, sym.FullyConnected(
        data, sym.var("aux_weight"), sym.var("aux_bias"),
        num_hidden=4, name="aux_head")])

    return [("mlp", mlp, {"data": (2, 784)}),
            ("convnet_bn", convnet, {"img": (2, 3, 8, 8)}),
            ("mlp_group", grouped, {"data": (2, 784)})]


def _trace(net, *input_shapes, names=None) -> Tuple[object, Dict]:
    """Initialize a HybridBlock and trace it to a Symbol (export seam)."""
    import mxnet_tpu as mx
    from .. import symbol as sym
    net.initialize(mx.init.Xavier())
    names = names or (["data"] if len(input_shapes) == 1 else
                      [f"data{i}" for i in range(len(input_shapes))])
    out = net(*[sym.var(n) for n in names])
    return out, dict(zip(names, input_shapes))


def traced_model_symbols(full: bool = False) \
        -> Iterator[Tuple[str, object, Dict[str, tuple]]]:
    """Traced symbols for the shipped model zoo.

    The default set keeps tier-1 CI fast; ``full=True`` adds more
    families (``tools/mxlint.py --models`` uses it).  The
    ``mxnet_tpu.models`` transformer families (BERT/Llama/NMT/SSD) read
    ``x.shape`` inside ``hybrid_forward`` — imperative-only, like the
    reference — so they have no Symbol form to lint; their graphs are
    covered imperatively by their own test files.
    """
    from ..gluon.model_zoo import get_model

    net = get_model("resnet18_v1", classes=10, thumbnail=True)
    yield ("zoo:resnet18_v1",) + _trace(net, (1, 3, 32, 32))

    if not full:
        return

    net = get_model("alexnet", classes=10)
    yield ("zoo:alexnet",) + _trace(net, (1, 3, 224, 224))

    net = get_model("mobilenet0.25", classes=10)
    yield ("zoo:mobilenet0.25",) + _trace(net, (1, 3, 224, 224))


def model_corpus(full: bool = False) \
        -> List[Tuple[str, object, Dict[str, tuple]]]:
    out = list(builtin_symbols())
    out.extend(traced_model_symbols(full=full))
    return out
