"""mxsan: the donation-lifetime & lock-order sanitizer (MXL7xx).

The stack's core runtime contracts are enforced by convention and by
crashing when violated: buffer donation ("the donated jax.Array is
dead after the call" — ``engine.get_compiled``), the poison→
``recover()`` protocol, and the one-dispatch steady state.  Meanwhile
five background threads (checkpoint writer, scrub daemon, guardian
watchdog, serving autoscaler, engine pipeline closer) coordinate
through ~20 module locks with no tool that can see a lock-order cycle
or a use-after-donate before it fires in production.  This module is
that tool — an OPT-IN runtime sanitizer in the TSan tradition
(reference ``src/engine_stress_tsan.cc``):

* **Leg 1 — buffer-lifetime sanitizer.**  A shadow state machine
  (live → in-flight → donated/dead) over the arrays the engine already
  tracks, hooked at the ``invoke_compiled`` / ``retrying_call`` /
  donation seams:

  - MXL701 — use-after-donate: a buffer a donated dispatch consumed is
    handed to another dispatch (attributed to the consuming op/owner);
  - MXL702 — the same buffer at two donate indices of one dispatch
    (XLA may alias both outputs onto one allocation);
  - MXL703 — a poisoned owner stepped without ``recover()``;
  - MXL704 — live-bytes leak vs the warmed baseline at shutdown
    (:func:`mark_baseline` / :func:`leak_check`).

* **Leg 2 — concurrency sanitizer.**  The known module locks
  (:data:`LOCK_SITES`) are swapped for instrumented wrappers that feed
  an acquisition-order graph and per-lock hold-time histograms:

  - MXL705 — a cycle in the acquisition-order graph (potential
    deadlock; ERROR severity);
  - MXL706 — a module lock held across a blocking device dispatch
    (stall hazard: every thread wanting the lock waits out the
    device).

Master switch: ``MXTPU_SANITIZE`` — ``0`` off (every seam pays one
attribute load), ``1`` collect findings + retained
``sanitizer_violation`` events, ``2`` additionally RAISE immediately
on a lifetime violation (MXL701/702) before the bad dispatch runs.
Lock findings (MXL705/706) are always collected, never raised — a
raise from inside a lock acquire would corrupt unrelated state.

Findings ride :func:`analysis.self_check` / ``tools/mxlint.py
--self-check`` via :func:`analyze_sanitizer`; the lock graph and
hold-time histograms land in :func:`report` and ``tools/mxsan.py
report``; the chaos soak (``elastic/chaos.py``) arms this module so
every fault/recovery/resize transition runs under the lifetime
checker.  See docs/static_analysis.md ("The sanitizer").
"""
from __future__ import annotations

import sys as _sys
import threading
import time
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from .findings import Finding

__all__ = ["configure", "level", "enabled", "reset",
           "pre_dispatch", "post_dispatch", "check_donation",
           "note_poisoned_step",
           "mark_baseline", "baseline", "leak_check",
           "instrument_locks", "restore_locks", "instrumented_locks",
           "held_locks", "lock_graph", "hold_stats",
           "records", "report", "analyze_sanitizer", "LOCK_SITES"]

#: the known module locks the concurrency leg instruments:
#: (module, attribute, label).  Adding a module lock to the codebase
#: should add a row here — the lock-order graph can only see what it
#: wraps.
LOCK_SITES: Tuple[Tuple[str, str, str], ...] = (
    ("mxnet_tpu.engine", "_lock", "engine._lock"),
    ("mxnet_tpu.engine", "_attr_lock", "engine._attr_lock"),
    ("mxnet_tpu.engine.persist", "_lock", "persist._lock"),
    ("mxnet_tpu.elastic.manager", "_SWAP_LOCK", "manager._SWAP_LOCK"),
    ("mxnet_tpu.elastic.manager", "_reg_lock", "manager._reg_lock"),
    ("mxnet_tpu.elastic.guardian", "_lock", "guardian._lock"),
    ("mxnet_tpu.elastic.faults", "_lock", "faults._lock"),
    ("mxnet_tpu.elastic.resize", "_reg_lock", "resize._reg_lock"),
    ("mxnet_tpu.elastic.integrity", "_scrub_lock",
     "integrity._scrub_lock"),
    ("mxnet_tpu.elastic.chaos", "_reg_lock", "chaos._reg_lock"),
    ("mxnet_tpu.telemetry.metrics", "_lock", "metrics._lock"),
    ("mxnet_tpu.telemetry.recorder", "_lock", "recorder._lock"),
    ("mxnet_tpu.telemetry.memory", "_lock", "memory._lock"),
    ("mxnet_tpu.telemetry.health", "_reg_lock", "health._reg_lock"),
    ("mxnet_tpu.serving.server", "_reg_lock", "server._reg_lock"),
    ("mxnet_tpu.parallel.planner", "_reg_lock", "planner._reg_lock"),
    ("mxnet_tpu.profiler", "_lock", "profiler._lock"),
    ("mxnet_tpu.gluon.compiled_step", "_fallback_lock",
     "compiled_step._fallback_lock"),
)

#: hold-time histogram boundaries (seconds); the last bucket is +inf
_HOLD_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0)

_MAX_RECORDS = 512
_MAX_SHADOW = 4096

# every sanitizer-internal structure takes RAW locks (never wrapped —
# wrapping the sanitizer's own bookkeeping would recurse)
_meta_lock = threading.Lock()
_rec_lock = threading.Lock()

_LEVEL = 0
_tls = threading.local()

#: id(buffer) -> shadow record for buffers a donated dispatch consumed.
#: The weakref disambiguates id reuse: a record only convicts when its
#: ref still points at the SAME object (a collected buffer's id can be
#: recycled by an unrelated allocation).
_shadow: "OrderedDict[int, dict]" = OrderedDict()

#: (rule, key) -> finding record (message, op/owner attribution, count)
_records: "OrderedDict[Tuple[str, str], dict]" = OrderedDict()

#: (held, acquired) -> {"count", "thread"} — the acquisition-order graph
_edges: Dict[Tuple[str, str], dict] = {}
#: lock label -> {"n", "total_s", "max_s", "buckets"} hold-time stats
_holds: Dict[str, dict] = {}
#: label -> (module, attr, raw lock) for every wrapped site
_wrapped: Dict[str, tuple] = {}

_baseline_bytes: Optional[int] = None

#: True while some record awaits its retained-event emission (was
#: detected under an instrumented lock) — the dispatch seams check
#: this one global before paying for a _flush_pending() walk
_has_pending = False


# -- switch ------------------------------------------------------------------

def level() -> int:
    """The active sanitizer level (0 off / 1 collect / 2 raise)."""
    return _LEVEL


def enabled() -> bool:
    return _LEVEL >= 1


def configure(lvl: Optional[int] = None) -> int:
    """Set the sanitizer level (``None`` re-reads ``MXTPU_SANITIZE``)
    and arm/disarm the seams: level >= 1 installs the engine dispatch
    hook and swaps the :data:`LOCK_SITES` for instrumented wrappers;
    level 0 restores both (one attribute load per seam remains)."""
    global _LEVEL
    if lvl is None:
        from .. import envs
        lvl = int(envs.get("MXTPU_SANITIZE"))
    lvl = max(0, min(2, int(lvl)))
    _LEVEL = lvl
    from .. import engine
    if lvl >= 1:
        engine._san = _sys.modules[__name__]
        instrument_locks()
    else:
        engine._san = None
        restore_locks()
    return lvl


def reset():
    """Forget findings, shadow state, the lock graph, hold stats, and
    the leak baseline (the armed/level state survives) — test
    isolation and per-soak hygiene."""
    global _baseline_bytes, _has_pending
    with _rec_lock:
        _records.clear()
        _has_pending = False
    with _meta_lock:
        _shadow.clear()
        _edges.clear()
        _holds.clear()
    _baseline_bytes = None


# -- finding plumbing --------------------------------------------------------

def _emit(rule: str, message: str, **fields):
    """Retained ``sanitizer_violation`` event + counter, re-entrancy
    guarded (the recorder/metrics locks are themselves instrumented:
    the emission must not record its own lock traffic) and never
    raising — forensics must not mask the violation.  Only called
    from :func:`_flush_pending`, i.e. never while the calling thread
    holds an instrumented lock."""
    _tls.in_san = True
    try:
        from .. import telemetry
        telemetry.counter(
            "mxtpu_sanitizer_violations_total",
            "distinct sanitizer (MXL7xx) violations recorded").inc()
        telemetry.record_event("sanitizer_violation", rule=rule,
                               message=message[:500], **fields)
    except Exception:
        pass
    finally:
        _tls.in_san = False


def _violation(rule: str, key: str, message: str, op=None, owner=None,
               raise_now: bool = False, **extra):
    global _has_pending
    owner_name = None
    if owner is not None:
        owner_name = getattr(owner, "name", None) or \
            type(owner).__name__
    with _rec_lock:
        rec = _records.get((rule, key))
        if rec is not None:
            rec["count"] += 1
            fresh = False
        else:
            fresh = len(_records) < _MAX_RECORDS
            if fresh:
                _records[(rule, key)] = {
                    "rule": rule, "message": message, "location": key,
                    "op": op, "owner": owner_name, "count": 1,
                    "ts": time.time(), "emitted": False, **extra}
                _has_pending = True
    if fresh:
        # the retained event must NOT be emitted while this thread
        # holds an instrumented lock: telemetry takes the (wrapped)
        # metrics/recorder locks, and MXL705/706 fire exactly when
        # such a lock IS held — re-acquiring it here would
        # self-deadlock.  Deferred records flush at the next safe
        # point (_flush_pending: a lock-free dispatch, report(), or
        # analyze_sanitizer()).
        if not getattr(_tls, "held", None):
            _flush_pending()
    if raise_now and _LEVEL >= 2:
        from ..base import MXNetError
        raise MXNetError(f"MXTPU_SANITIZE=2: {rule}: {message}")


def _flush_pending():
    """Emit the retained event for every recorded violation that could
    not emit at detection time (detected under an instrumented lock).
    Called from every lock-free seam that can afford it: a violation
    on an unlocked thread, the dispatch hooks, ``report()`` and
    ``analyze_sanitizer()``."""
    global _has_pending
    if getattr(_tls, "held", None):
        return
    pending = []
    with _rec_lock:
        for rec in _records.values():
            if not rec.get("emitted"):
                rec["emitted"] = True
                pending.append(dict(rec))
        _has_pending = False
    for rec in pending:
        extra = {k: v for k, v in rec.items()
                 if k in ("locks", "cycle", "donor_op", "donor_owner",
                          "live_bytes", "baseline_bytes")}
        _emit(rec["rule"], rec["message"], op=rec.get("op"),
              owner=rec.get("owner"), **extra)


def records() -> List[dict]:
    """Snapshot of the recorded violations (the MXL7xx finding
    input)."""
    with _rec_lock:
        return [dict(r) for r in _records.values()]


# -- leg 1: buffer lifetime --------------------------------------------------

def _is_deleted(a) -> bool:
    try:
        return bool(a.is_deleted())
    except Exception:
        return False


def pre_dispatch(op: str, arrays, donate=None, owner=None):
    """Dispatch-entry hook (``engine.invoke_compiled`` and the SPMD
    trainer's fused seams): use-after-donate (MXL701) over every
    input, double donation (MXL702) over the donate indices, and
    lock-held-across-dispatch (MXL706) for the calling thread."""
    if not _LEVEL:
        return
    held = getattr(_tls, "held", None)
    if _has_pending and not held:
        # a lock-free dispatch is the flush seam the deferred
        # MXL705/706 retained events wait for
        _flush_pending()
    if held:
        names = [h for h, _t in held]
        _violation(
            "MXL706", f"san:lock-across-dispatch:{names[-1]}:{op}",
            f"dispatch of {op!r} while holding module lock(s) "
            f"{names}: the device round-trip stalls every thread "
            "waiting on them; move the dispatch outside the lock",
            op=op, owner=owner, locks=names)
    for i, a in enumerate(arrays):
        rec = _shadow.get(id(a))
        if rec is not None:
            if rec["ref"]() is a:
                _violation(
                    "MXL701", f"san:use-after-donate:{op}:{i}",
                    f"input #{i} of {op!r} was already donated to "
                    f"{rec['op']!r}"
                    + (f" (owner {rec['owner']})" if rec.get("owner")
                       else "")
                    + " — the buffer is dead; rebind the caller to "
                    "the dispatch's OUTPUT instead of the consumed "
                    "input (docs/static_analysis.md, 'The "
                    "sanitizer')",
                    op=op, owner=owner, donor_op=rec["op"],
                    donor_owner=rec.get("owner"), raise_now=True)
            else:
                # id recycled by an unrelated object: drop stale row
                with _meta_lock:
                    _shadow.pop(id(a), None)
        elif _is_deleted(a):
            _violation(
                "MXL701", f"san:use-after-donate:{op}:{i}",
                f"input #{i} of {op!r} is already deleted (donated "
                "by an untracked dispatch or explicitly freed) — "
                "the dispatch would read dead memory",
                op=op, owner=owner, raise_now=True)
    if donate:
        check_donation(op, arrays, donate, owner=owner)


def check_donation(op: str, arrays, donate, owner=None):
    """MXL702 — the same buffer at two donate indices of one dispatch
    (``donate=None`` means every array is donated, the SPMD trainer's
    pre-filtered set)."""
    if not _LEVEL:
        return
    idx = donate if donate is not None else range(len(arrays))
    seen: Dict[int, int] = {}
    for j in idx:
        if j >= len(arrays):
            continue
        k = id(arrays[j])
        if k in seen:
            _violation(
                "MXL702", f"san:double-donate:{op}:{seen[k]}:{j}",
                f"{op!r} donates the SAME buffer at indices "
                f"{seen[k]} and {j}: XLA may alias both outputs "
                "onto one allocation — pass distinct buffers or "
                "drop one index from donate_argnums",
                op=op, owner=owner, raise_now=True)
        else:
            seen[k] = j


def post_dispatch(op: str, arrays, donate=None, owner=None):
    """Dispatch-success hook: the donated inputs are now dead — record
    them in the shadow table with op/owner attribution so a later use
    convicts with a name, not a bare jax deleted-buffer error.
    ``donate=None`` means every array in ``arrays`` was donated (the
    SPMD trainer passes its pre-filtered donated set)."""
    if not _LEVEL:
        return
    if _has_pending and not getattr(_tls, "held", None):
        _flush_pending()
    owner_name = None
    if owner is not None:
        owner_name = getattr(owner, "name", None) or \
            type(owner).__name__
    idx = donate if donate is not None else range(len(arrays))
    now = time.time()
    with _meta_lock:
        for j in idx:
            if j >= len(arrays):
                continue
            a = arrays[j]
            try:
                ref = weakref.ref(a)
            except TypeError:
                continue            # not a buffer (python scalar, ...)
            _shadow[id(a)] = {"ref": ref, "op": op,
                              "owner": owner_name, "ts": now}
        if len(_shadow) > _MAX_SHADOW:
            # collected buffers first (their id may be recycled),
            # then oldest records
            for k in [k for k, r in _shadow.items()
                      if r["ref"]() is None]:
                del _shadow[k]
            while len(_shadow) > _MAX_SHADOW:
                _shadow.popitem(last=False)


def note_poisoned_step(owner, where: str, error) -> None:
    """MXL703 — an owner whose donated state is gone was stepped
    without ``recover()``.  Called by the step paths right before
    their poisoned-owner raise (the raise still happens at every
    level; the finding is the audit trail)."""
    if not _LEVEL:
        return
    _violation(
        "MXL703", f"san:poisoned-step:{where}",
        f"{where}: a poisoned owner was stepped without recover() — "
        "its donated state was consumed by a failed dispatch "
        f"({str(error)[:200]}); call recover(manager) first "
        "(docs/elasticity.md)",
        op=where, owner=owner)


def mark_baseline(nbytes: Optional[int] = None) -> int:
    """Record the warmed live-bytes baseline the shutdown leak check
    (MXL704) compares against — call once the steady state is reached
    (after warm-up, like the chaos soak does)."""
    global _baseline_bytes
    if nbytes is None:
        from .. import engine
        nbytes = engine.live_bytes()
    _baseline_bytes = int(nbytes)
    return _baseline_bytes


def baseline() -> Optional[int]:
    return _baseline_bytes


def leak_check(slack_bytes: int = 2 << 20,
               factor: float = 2.0) -> Optional[dict]:
    """MXL704 — compare the current tracked live-bytes census against
    the :func:`mark_baseline` snapshot (leak when ``live > baseline *
    factor + slack_bytes``, the chaos soak's tolerance).  Returns the
    violation record, or ``None`` when clean / no baseline marked."""
    if _baseline_bytes is None:
        return None
    from .. import engine
    live = engine.live_bytes()
    if live <= _baseline_bytes * factor + slack_bytes:
        return None
    _violation(
        "MXL704", "san:live-bytes-leak",
        f"tracked live buffers ended at {live} bytes vs the warmed "
        f"baseline {_baseline_bytes} (tolerance x{factor} + "
        f"{slack_bytes}): buffers are pinned past their step — a "
        "stale reference is holding donation's HBM savings hostage",
        live_bytes=live, baseline_bytes=_baseline_bytes)
    return {"live_bytes": live, "baseline_bytes": _baseline_bytes}


# -- leg 2: lock order -------------------------------------------------------

class SanLock:
    """Instrumented stand-in for a module ``threading.Lock``: delegates
    to the SAME underlying lock (so pre-swap references interoperate)
    and feeds the acquisition-order graph + hold-time stats."""

    __slots__ = ("_raw", "name")

    def __init__(self, raw, name: str):
        self._raw = raw
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            _note_acquire(self.name)
        return ok

    def release(self):
        _note_release(self.name)
        self._raw.release()

    def locked(self):
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def _note_acquire(name: str):
    tls = _tls
    if getattr(tls, "in_san", False):
        return
    held = getattr(tls, "held", None)
    if held is None:
        held = tls.held = []
    if held:
        cycles = []
        with _meta_lock:
            for h, _t in held:
                if h == name:
                    continue
                e = _edges.get((h, name))
                if e is None:
                    _edges[(h, name)] = {
                        "count": 1,
                        "thread": threading.current_thread().name}
                    cyc = _find_cycle_locked(name, h)
                    if cyc:
                        cycles.append(cyc)
                else:
                    e["count"] += 1
        for cyc in cycles:
            _violation(
                "MXL705",
                "san:lock-cycle:" + ">".join(sorted(set(cyc))),
                "lock-order cycle " + " -> ".join(cyc) + ": these "
                "locks are acquired in inconsistent order on "
                "different threads — two of them interleaving is a "
                "deadlock; pick one order (docs/static_analysis.md, "
                "'The sanitizer')",
                cycle=cyc)
    held.append((name, time.perf_counter()))


def _find_cycle_locked(src: str, dst: str) -> Optional[List[str]]:
    """Path ``src -> ... -> dst`` through the edge set (caller holds
    ``_meta_lock``); with the new edge ``dst -> src`` just added, a
    found path closes a cycle."""
    succ: Dict[str, list] = {}
    for (a, b) in _edges:
        succ.setdefault(a, []).append(b)
    stack = [(src, [dst, src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in succ.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_release(name: str):
    tls = _tls
    if getattr(tls, "in_san", False):
        return
    held = getattr(tls, "held", None)
    if not held:
        return
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] == name:
            _n, t0 = held.pop(i)
            dt = time.perf_counter() - t0
            with _meta_lock:
                st = _holds.get(name)
                if st is None:
                    st = _holds[name] = {
                        "n": 0, "total_s": 0.0, "max_s": 0.0,
                        "buckets": [0] * (len(_HOLD_BUCKETS) + 1)}
                st["n"] += 1
                st["total_s"] += dt
                if dt > st["max_s"]:
                    st["max_s"] = dt
                for bi, bound in enumerate(_HOLD_BUCKETS):
                    if dt <= bound:
                        st["buckets"][bi] += 1
                        break
                else:
                    st["buckets"][-1] += 1
            return


def instrument_locks() -> List[str]:
    """Swap every :data:`LOCK_SITES` module lock for a :class:`SanLock`
    wrapper (idempotent; the wrapper delegates to the same underlying
    lock, so references captured before the swap stay coherent).
    Returns the labels instrumented."""
    import importlib
    out = []
    for mod_name, attr, label in LOCK_SITES:
        try:
            mod = importlib.import_module(mod_name)
        except Exception:
            continue                # optional surface not importable
        cur = getattr(mod, attr, None)
        if cur is None or isinstance(cur, SanLock):
            continue
        setattr(mod, attr, SanLock(cur, label))
        _wrapped[label] = (mod, attr, cur)
        out.append(label)
    return out


def restore_locks():
    """Put the raw locks back (wrappers already handed out keep
    working — they delegate to the same lock object)."""
    for label, (mod, attr, raw) in list(_wrapped.items()):
        if isinstance(getattr(mod, attr, None), SanLock):
            setattr(mod, attr, raw)
        del _wrapped[label]


def instrumented_locks() -> List[str]:
    return sorted(_wrapped)


def held_locks() -> List[str]:
    """Instrumented locks the CALLING thread currently holds."""
    return [h for h, _t in getattr(_tls, "held", ())]


def lock_graph() -> dict:
    """The acquisition-order graph: edges with counts + the recorded
    cycles (``tools/mxsan.py report`` renders this)."""
    with _meta_lock:
        edges = [{"from": a, "to": b, **e}
                 for (a, b), e in sorted(_edges.items())]
    cycles = [r.get("cycle") for r in records()
              if r["rule"] == "MXL705"]
    return {"edges": edges, "cycles": cycles}


def hold_stats() -> Dict[str, dict]:
    """Per-lock hold-time stats (count/total/max + the fixed-bucket
    histogram over :data:`_HOLD_BUCKETS` seconds)."""
    with _meta_lock:
        return {k: {**v, "buckets": list(v["buckets"]),
                    "bucket_bounds_s": list(_HOLD_BUCKETS)}
                for k, v in sorted(_holds.items())}


# -- reporting ---------------------------------------------------------------

def report() -> dict:
    """``cache_info()``-style snapshot of both legs: level/armed
    state, the shadow table + leak baseline, the lock graph +
    hold-time histograms, and every recorded violation."""
    from .. import engine
    _flush_pending()
    recs = records()
    counts: Dict[str, int] = {}
    for r in recs:
        counts[r["rule"]] = counts.get(r["rule"], 0) + r["count"]
    with _meta_lock:
        shadow_n = len(_shadow)
    return {
        "level": _LEVEL,
        "armed": _LEVEL >= 1,
        "lifetime": {
            "donated_tracked": shadow_n,
            "baseline_bytes": _baseline_bytes,
            "live_bytes": engine.live_bytes(),
        },
        "locks": {
            "instrumented": instrumented_locks(),
            **lock_graph(),
            "holds": hold_stats(),
        },
        "counts": counts,
        "findings": recs,
    }


def analyze_sanitizer() -> List[Finding]:
    """One mxlint finding per recorded MXL70x violation (plus a fresh
    MXL704 check when a baseline was marked) — rides
    ``analysis.self_check()`` / ``tools/mxlint.py --self-check``.
    Free in a fresh process: nothing armed, nothing recorded."""
    if _LEVEL >= 1:
        leak_check()
    _flush_pending()
    out = []
    for r in records():
        msg = r["message"]
        if r["count"] > 1:
            msg += f" (x{r['count']})"
        out.append(Finding(r["rule"], msg, r["location"]))
    return out
