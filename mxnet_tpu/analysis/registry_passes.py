"""Registry passes over every OpDef (rules MXL2xx).

``registry.register()`` enforces the signature contracts at registration
time; mxlint re-runs the same checks offline (catching OpDefs built by
hand or monkeypatched in tests) and adds the checks registration cannot
do cheaply: nd/sym namespace symmetry, alias integrity, best-effort
``num_outputs`` verification against literal tuple returns, and
unhashable default attrs (which silently degrade the jit-cache key to
the recursive ``_freeze`` path or duplicate cache entries).
"""
from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List, Optional

from .findings import Finding

__all__ = ["analyze_registry", "analyze_opdef"]


def _tuple_return_len(fcompute) -> Optional[int]:
    """If every ``return`` in fcompute is a tuple literal of one
    consistent length, return that length; None when undecidable
    (helpers, conditionals returning names, lambdas, partials)."""
    try:
        src = textwrap.dedent(inspect.getsource(fcompute))
        tree = ast.parse(src)
    except (TypeError, OSError, SyntaxError, IndentationError, ValueError):
        return None
    fns = [n for n in tree.body
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    if len(fns) != 1:
        return None
    returns = [n for n in ast.walk(fns[0]) if isinstance(n, ast.Return)]
    if not returns:
        return None
    lengths = set()
    for r in returns:
        if not isinstance(r.value, ast.Tuple):
            return None
        if any(isinstance(e, ast.Starred) for e in r.value.elts):
            return None
        lengths.add(len(r.value.elts))
    return lengths.pop() if len(lengths) == 1 else None


def analyze_opdef(op, anchor: Optional[str] = None) -> List[Finding]:
    """MXL201-204/206 for one OpDef."""
    from ..ops.registry import validate_opdef
    anchor = anchor or f"op:{op.name}"
    out: List[Finding] = []
    kind_to_rule = {"arity": "MXL201", "scalar_attrs": "MXL202",
                    "scalar_ref_input": "MXL203", "num_outputs": "MXL204"}
    for kind, problem in validate_opdef(op):
        out.append(Finding(kind_to_rule[kind], problem, anchor))

    n_ret = _tuple_return_len(op.fcompute)
    if n_ret is not None and op.num_outputs not in (-1, n_ret) \
            and n_ret > 1:
        out.append(Finding(
            "MXL204", f"fcompute returns a {n_ret}-tuple on every path "
            f"but num_outputs={op.num_outputs}", anchor))

    try:
        sig = inspect.signature(op.fcompute)
    except (TypeError, ValueError):
        return out
    for p in sig.parameters.values():
        if p.default is inspect.Parameter.empty:
            continue
        try:
            hash(p.default)
        except TypeError:
            out.append(Finding(
                "MXL206", f"default {p.name}={p.default!r} is unhashable: "
                "every call pays the recursive _freeze key path (or "
                "duplicates jit-cache entries per call site)", anchor))
    return out


def analyze_registry() -> List[Finding]:
    """Run every registry pass over the live op registry."""
    from ..ops.registry import _ALIASES, _REGISTRY
    findings: List[Finding] = []
    for name in sorted(_REGISTRY):
        findings.extend(analyze_opdef(_REGISTRY[name]))

    for alias_name, target in sorted(_ALIASES.items()):
        if target not in _REGISTRY:
            findings.append(Finding(
                "MXL207", f"alias {alias_name!r} targets unregistered op "
                f"{target!r}", f"op:{alias_name}"))

    # nd/sym namespace symmetry: the reference codegens both frontends
    # from one registry; an op visible in only one namespace breaks
    # hybridize (imperative call works, symbolic trace AttributeErrors)
    from .. import ndarray as nd_mod
    from .. import symbol as sym_mod
    from ..ops.registry import list_ops
    for name in list_ops():
        in_nd = hasattr(nd_mod, name)
        in_sym = hasattr(sym_mod, name)
        if in_nd != in_sym:
            where = "nd only" if in_nd else "sym only"
            findings.append(Finding(
                "MXL205", f"op {name!r} exposed in {where}; hybridized "
                "blocks need both namespaces", f"op:{name}"))
    return findings
