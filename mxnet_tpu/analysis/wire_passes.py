"""mxwire: the jaxpr-level wire-leg auditor (MXL8xx;
docs/static_analysis.md, "The wire auditor").

Every compiled fused-step variant — train single / ``step_multi``,
the ZeRO stages, the compressed paths, serving prefill/decode —
registers an abstract signature here (:func:`note_step`, riding the
same seams that feed ``planner.note_plan`` and the memory
observatory).  The auditor traces each variant's **closed jaxpr**
lazily and walks it into a **wire-leg inventory**: every collective
primitive (``psum``, ``psum_scatter``/``reduce_scatter``,
``all_gather``, ``all_to_all``, ``ppermute``) classified by leg kind
— dp grad sync, ZeRO scatter/gather, tp activation, decode — via its
axis names resolved through the live :class:`ShardingPlan`, with wire
dtype, payload bytes, and analytic bytes-on-wire (the SAME ring
formulas the memory observatory applies to compiled HLO —
``telemetry.memory._wire_bytes`` — so the static and runtime
accountings are commensurable by construction).

The rules (:func:`analyze_wire`, riding ``self_check()`` /
``mxlint --self-check``; standalone: ``tools/mxwire.py``):

* **MXL801** (error) — a leg whose ON-WIRE dtype is wider than the
  plan's declared ``precision`` for that leg kind: the silent
  fp32-widening class (a "quantized" grad leg paying full-width
  bytes).  Sub-4KiB payloads are exempt (the fp32 scale lanes every
  block-scaled scheme ships beside its codes), as are ``stats`` /
  ``scalar`` legs.
* **MXL802** (error) — a full all-reduce surviving on a ZeRO-2 grad
  leg: the stage-2 wire contract is reduce-scatter + all-gather;
  a grad-sized ungated ``psum`` over the dp axis there moves the
  whole gradient anyway (previously a runtime wire-assertion, now
  static).
* **MXL803** (warning) — an observability-only collective (a leg
  whose outputs feed ONLY the health/stats outputs — a backward
  liveness slice finds them) executing OUTSIDE any ``lax.cond``
  sampling gate in a variant registered as sampled: the
  integrity/health spec claims those rows are gated, so an ungated
  one pays unsampled wire cost every step.
* **MXL804** (warning) — the static bytes-on-wire total diverging
  more than ``drift`` (default 10%) from the memory observatory's
  runtime accounting for the same program: either the static model
  or the runtime counter is lying, and both feed the compression
  -ratio claims.

Free in a fresh process (empty registry — the CI gate stays quiet);
``MXTPU_WIRE_AUDIT=0`` disables registration entirely.  Registration
stores ONLY aval signatures (``jax.ShapeDtypeStruct``) — never live
arrays, so noting a variant cannot pin HBM.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from .findings import Finding

__all__ = ["note_step", "variants", "analyze_wire", "wire_report",
           "SCALAR_LEG_BYTES"]

_lock = threading.Lock()
#: (owner, variant) -> registered record
_variants: Dict[Tuple[str, str], dict] = {}

#: jaxpr collective primitive -> the HLO op name the observatory's
#: analytic ring model (``telemetry.memory._wire_bytes``) speaks
_COLLECTIVE_HLO = {
    "psum": "all-reduce",
    "pmax": "all-reduce",
    "pmin": "all-reduce",
    "reduce_scatter": "reduce-scatter",
    "all_gather": "all-gather",
    "all_to_all": "all-to-all",
    "ppermute": "collective-permute",
    "pbroadcast": "collective-broadcast",
}

#: a load-bearing dp reduction below this is a ``scalar`` leg (loss /
#: aux pmeans, the fp32 scale lanes riding beside quantized codes) —
#: inventoried, never precision-audited
SCALAR_LEG_BYTES = 4096
#: MXL802 only fires on grad-sized payloads: healthy stage-2 variants
#: still psum tiny stats rows under their sampling gate
_MXL802_FLOOR = 16384


# -- registry ---------------------------------------------------------------

def _aval(v):
    """One value -> its abstract signature (never holds the array)."""
    import jax
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
    return v                    # python scalar: weak-typed, no HBM


def note_step(owner: str, variant: str, fn, vals, *,
              plan=None, mesh_axes=None, dp_axis: Optional[str] = None,
              zero_stage: int = 0, compressed: bool = False,
              sampled: bool = False, kind: str = "train",
              program: Optional[str] = None, params_bytes=None,
              obs_outputs=()) -> None:
    """Register one compiled step variant for the wire audit.

    ``fn`` is the PURE python step function (what the trainer jits),
    ``vals`` its example positional arguments — converted to
    ``ShapeDtypeStruct`` immediately; the jaxpr is traced lazily at
    audit time.  ``program`` names the memory-observatory record the
    MXL804 reconciliation reads; ``params_bytes`` is the
    ``[(name, nbytes, dtype_name)]`` trainable census the derived
    dense-dp leg model needs (GSPMD inserts the grad all-reduce
    implicitly, so a dense jaxpr carries no collective to walk);
    ``obs_outputs`` are the (negative-ok) flat output indices that
    are observability-only (the health vector).  Never raises
    (telemetry-grade); ``MXTPU_WIRE_AUDIT=0`` makes it a no-op.
    """
    try:
        from .. import envs
        if fn is None or not envs.get("MXTPU_WIRE_AUDIT"):
            return
        import jax
        # vals are pytrees (the trainers pass nested tuples): convert
        # every LEAF, or the registry would pin the live arrays
        avals = jax.tree_util.tree_map(_aval, tuple(vals))
        axes = dict(mesh_axes) if mesh_axes else \
            dict(getattr(plan, "axes", None) or {})
        rec = {
            "owner": str(owner), "variant": str(variant),
            "fn": fn, "avals": avals, "plan": plan,
            "mesh_axes": axes,
            "dp_axis": str(dp_axis) if dp_axis else
            str(getattr(plan, "dp_axis", "dp")),
            "zero_stage": int(zero_stage or 0),
            "compressed": bool(compressed),
            "sampled": bool(sampled), "kind": str(kind),
            "program": None if program is None else str(program),
            "params_bytes": [(str(n), int(b), str(d))
                             for n, b, d in (params_bytes or ())],
            "obs_outputs": tuple(int(i) for i in (obs_outputs or ())),
            "jaxpr": None, "trace_error": None, "legs": None,
        }
        with _lock:
            _variants[(rec["owner"], rec["variant"])] = rec
    except Exception:
        pass


def variants() -> Dict[Tuple[str, str], dict]:
    """Registered variants (shallow copies; ``legs``/``jaxpr`` may be
    unpopulated until an audit ran)."""
    with _lock:
        return {k: dict(v) for k, v in _variants.items()}


def _reset():
    """Test hook."""
    with _lock:
        _variants.clear()


# -- the jaxpr walk ---------------------------------------------------------

def _traced(rec):
    """The variant's closed jaxpr, traced once and cached; ``None``
    (with ``trace_error`` set and a telemetry event) when the pure fn
    cannot be abstractly traced."""
    if rec.get("jaxpr") is not None or rec.get("trace_error"):
        return rec.get("jaxpr")
    import jax
    try:
        rec["jaxpr"] = jax.make_jaxpr(rec["fn"])(*rec["avals"])
    except Exception as e:
        rec["trace_error"] = repr(e)[:300]
        try:
            from ..telemetry import record_event
            record_event("wire_trace_unavailable",
                         owner=rec["owner"], variant=rec["variant"],
                         error=rec["trace_error"])
        except Exception:
            pass
    return rec.get("jaxpr")


def _eqn_axes(eqn) -> tuple:
    """Named axes one collective eqn reduces/moves over (``psum``
    spells them ``axes``, the others ``axis_name`` — which
    ``all_to_all`` carries as a bare string, the rest as a tuple)."""
    p = eqn.params
    ax = p.get("axes")
    if ax is None:
        ax = p.get("axis_name")
    if ax is None:
        return ()
    if not isinstance(ax, (tuple, list)):
        ax = (ax,)
    return tuple(a for a in ax if isinstance(a, str))


def _sub_jaxprs(eqn) -> list:
    """Every sub-jaxpr an eqn carries (pjit/scan/while ClosedJaxprs,
    shard_map's plain Jaxpr, cond's branch tuple), as plain Jaxprs."""
    import jax
    out = []
    for v in eqn.params.values():
        items = v if isinstance(v, (tuple, list)) else (v,)
        for item in items:
            if isinstance(item, jax.core.Jaxpr):
                out.append(item)
            elif isinstance(item, jax.core.ClosedJaxpr):
                out.append(item.jaxpr)
    return out


def _note_leg(eqn, gated, obs_only, mesh_axes, legs):
    import numpy as np
    from ..telemetry.memory import _wire_bytes
    axes = _eqn_axes(eqn)
    if not axes:
        return                  # positional (vmap) axes: not wire
    op = _COLLECTIVE_HLO[eqn.primitive.name]
    k = 1
    for ax in axes:
        k *= int(mesh_axes.get(ax, 1))
    payload, itemsize, dtype = 0, 0, None
    for v in eqn.outvars:
        aval = getattr(v, "aval", None)
        shape = getattr(aval, "shape", None)
        dt = getattr(aval, "dtype", None)
        if shape is None or dt is None:
            continue
        dt = np.dtype(dt)
        n = 1
        for d in shape:
            n *= int(d)
        payload += n * dt.itemsize
        if dt.itemsize > itemsize:
            itemsize, dtype = int(dt.itemsize), str(dt.name)
    legs.append({
        "primitive": eqn.primitive.name, "op": op, "axes": axes,
        "group": k, "dtype": dtype, "itemsize": itemsize,
        "payload_bytes": int(payload),
        "wire_bytes": int(_wire_bytes(op, payload, k)),
        "gated": bool(gated), "obs_only": bool(obs_only),
        "implicit": False,
    })


def _walk(jaxpr, obs_idx, gated, mesh_axes, legs, state):
    """One jaxpr level: a backward liveness pass splits every var into
    primal-live / obs-live (relative to this level's ``obs_idx``
    output positions), so a collective whose outputs feed ONLY the
    observability outputs is tagged ``obs_only``; dead eqns (XLA DCEs
    them — the compiled HLO carries no trace) are skipped entirely.
    Descends into pjit/scan/while bodies once and BOTH cond branches
    (matching ``collective_stats``'s count-per-HLO-text-appearance
    convention, so MXL804 compares like with like); cond branches are
    ``gated``; shard_map scopes its own mesh axis sizes."""
    import jax
    Literal = jax.core.Literal
    n_out = len(jaxpr.outvars)
    idxset = {i % n_out for i in obs_idx} if (n_out and obs_idx) \
        else set()
    primal, obs = set(), set()
    for i, v in enumerate(jaxpr.outvars):
        if isinstance(v, Literal):
            continue
        (obs if i in idxset else primal).add(v)
    for eqn in reversed(jaxpr.eqns):
        flags = [((v in primal), (v in obs)) for v in eqn.outvars]
        p_live = any(p for p, _o in flags)
        o_live = any(o for _p, o in flags)
        if not (p_live or o_live):
            continue
        for v in eqn.invars:
            if isinstance(v, Literal):
                continue
            if p_live:
                primal.add(v)
            if o_live:
                obs.add(v)
        name = eqn.primitive.name
        if name in _COLLECTIVE_HLO:
            _note_leg(eqn, gated, o_live and not p_live,
                      mesh_axes, legs)
            continue
        subs = _sub_jaxprs(eqn)
        if not subs:
            continue
        sub_mesh = mesh_axes
        if name == "shard_map":
            state["shard_map"] = True
            shape = getattr(eqn.params.get("mesh"), "shape", None)
            if shape:
                sub_mesh = dict(shape)
        sub_gated = gated or name == "cond"
        for sub in subs:
            if len(sub.outvars) == len(eqn.outvars):
                # 1:1 outvar mapping (pjit, cond branches, shard_map;
                # scan: carry + ys line up positionally on both sides)
                sub_obs = tuple(i for i, (p, o) in enumerate(flags)
                                if o and not p)
            elif o_live and not p_live:
                sub_obs = tuple(range(len(sub.outvars)))
            else:
                sub_obs = ()    # conservative: treat all as primal
            _walk(sub, sub_obs, sub_gated, sub_mesh, legs, state)


# -- classification ---------------------------------------------------------

def _classify(leg, rec) -> str:
    """Leg kind via the plan's role axes: ``stats`` (obs-only) >
    serving ``decode`` > ``tp_act``/``pp``/``sp`` > the dp branch
    (``scalar`` below 4KiB; under ZeRO, reduce-scatter/all-gather are
    the ``zero_scatter``/``zero_gather`` contract legs; every other
    dp collective — including a quantized path's all-gather of int8
    codes at stage 0 — is grad sync)."""
    if leg["obs_only"]:
        return "stats"
    if rec.get("kind") != "train":
        return "decode"
    plan = rec.get("plan")
    dp = rec.get("dp_axis") or getattr(plan, "dp_axis", "dp")
    axes = set(leg["axes"])
    if getattr(plan, "tp_axis", "tp") in axes:
        return "tp_act"
    if getattr(plan, "pp_axis", "pp") in axes:
        return "pp"
    if getattr(plan, "sp_axis", "sp") in axes:
        return "sp"
    if dp in axes:
        if leg["payload_bytes"] < SCALAR_LEG_BYTES:
            return "scalar"
        if int(rec.get("zero_stage") or 0) >= 1:
            if leg["op"] == "reduce-scatter":
                return "zero_scatter"
            if leg["op"] == "all-gather":
                return "zero_gather"
        return "dp_grad"
    return "other"


def _implicit_legs(rec) -> list:
    """The derived dense-dp model: under plain jit + GSPMD the grad
    all-reduce never appears in the jaxpr (the partitioner inserts
    it), so a dense dp-only variant gets one implicit all-reduce leg
    per trainable param — payload = the param's global bytes, dtype =
    the param dtype.  This is what makes MXL801 and the MXL804
    reconciliation reach the dense path at all."""
    import numpy as np
    from ..telemetry.memory import _wire_bytes
    dp = rec.get("dp_axis") or "dp"
    k = int((rec.get("mesh_axes") or {}).get(dp, 1))
    legs = []
    if k <= 1:
        return legs
    for name, nbytes, dtype in rec.get("params_bytes") or ():
        try:
            itemsize = int(np.dtype(dtype).itemsize)
        except TypeError:
            itemsize, dtype = 4, "float32"
        legs.append({
            "primitive": "psum", "op": "all-reduce", "axes": (dp,),
            "group": k, "dtype": dtype, "itemsize": itemsize,
            "payload_bytes": int(nbytes),
            "wire_bytes": int(_wire_bytes("all-reduce", nbytes, k)),
            "gated": False, "obs_only": False,
            "implicit": True, "param": name,
            "kind": ("dp_grad" if nbytes >= SCALAR_LEG_BYTES
                     else "scalar"),
        })
    return legs


def _legs_for(rec) -> Tuple[list, bool]:
    """``(legs, derived)`` for one registered variant: the walked
    inventory, with the implicit dense-dp grad model APPENDED when the
    trace carries no load-bearing explicit leg (the health plane's
    gated stats rows appear explicitly — inside their own nested
    shard_map — even in a dense jaxpr, but the grad all-reduce stays
    GSPMD-implicit; ZeRO/compressed variants carry their grad legs
    explicitly and derive nothing).  Dense tp>1 also derives nothing —
    GSPMD's tensor-parallel activation traffic is not modelable from
    the jaxpr, so neither MXL801 nor MXL804 can speak to it."""
    if rec.get("legs") is not None:
        return rec["legs"], bool(rec.get("derived"))
    legs: list = []
    state = {"shard_map": False}
    closed = _traced(rec)
    if closed is not None:
        _walk(closed.jaxpr, rec.get("obs_outputs") or (), False,
              dict(rec.get("mesh_axes") or {}), legs, state)
        for leg in legs:
            leg["kind"] = _classify(leg, rec)
    derived = False
    # "load-bearing": any explicit leg that moves primal data at
    # above-scalar size — a grad psum, a ZeRO scatter/gather, a
    # compressed-wire leg.  Obs-only stats rows and sub-4KiB scalars
    # never carry the gradient, so their presence must not suppress
    # the implicit dense-dp model.
    load_bearing = any(
        (not leg["obs_only"]) and
        leg["payload_bytes"] >= SCALAR_LEG_BYTES
        for leg in legs)
    if not load_bearing and \
            rec.get("kind") == "train" and rec.get("params_bytes"):
        axes = rec.get("mesh_axes") or {}
        dp = rec.get("dp_axis") or "dp"
        if all(int(v) == 1 for a, v in axes.items() if a != dp):
            legs = legs + _implicit_legs(rec)
            derived = True
    rec["legs"], rec["derived"] = legs, derived
    return legs, derived


# -- the rules --------------------------------------------------------------

def _measured_wire(rec) -> Optional[int]:
    """The observatory's runtime bytes-on-wire for this variant's
    program, or ``None`` when it was never harvested."""
    name = rec.get("program")
    if not name:
        return None
    from ..telemetry import memory as _memory
    prog = _memory.programs().get(name)
    if prog is None:
        return None
    return int(prog.get("collective_wire_bytes") or 0)


def _reconcile_eligible(rec, legs, derived) -> bool:
    """MXL804 compares only where the static model is complete: the
    derived dense dp-only model, or a variant whose GRAD wire is
    explicit in the jaxpr (the shard_map'd ZeRO/quantized steps).
    Compressed paths dispatch outside the tiered AOT seam (never
    harvested) and dense tp>1 has unmodelable GSPMD activation
    traffic riding beside an implicit grad all-reduce — a stats-only
    or tp-only explicit inventory is NOT a complete model, so both
    skip."""
    if rec.get("kind") != "train" or rec.get("compressed"):
        return False
    if derived:
        return True
    return any((not leg["implicit"]) and leg["kind"] in
               ("dp_grad", "zero_scatter", "zero_gather")
               for leg in legs)


def _audit_one(rec, drift: float,
               measured_override: Optional[int] = None
               ) -> List[Finding]:
    owner, variant = rec["owner"], rec["variant"]
    loc = f"wire:{owner}:{variant}" if variant else f"wire:{owner}"
    findings: List[Finding] = []
    legs, derived = _legs_for(rec)
    if rec.get("trace_error"):
        return findings         # fail-open; event already recorded
    plan = rec.get("plan")
    prec = getattr(plan, "precision", None) or {}
    zero_stage = int(rec.get("zero_stage") or 0)
    for leg in legs:
        kind, ax = leg["kind"], "/".join(leg["axes"])
        # MXL801 — wire dtype wider than the plan's declaration for
        # this leg kind.  Sub-4KiB payloads (scale lanes) are exempt;
        # stats/scalar/other kinds are never declarable.
        want = prec.get(kind)
        if want is not None and \
                leg["payload_bytes"] >= SCALAR_LEG_BYTES:
            from ..parallel.planner import wire_dtype_itemsize
            want_size = wire_dtype_itemsize(want)
            if leg["itemsize"] > want_size:
                what = f"param {leg['param']!r}" if leg.get("param") \
                    else f"a {leg['primitive']}"
                findings.append(Finding(
                    "MXL801",
                    f"{owner}:{variant or 'step'}: {kind} leg over "
                    f"axis {ax!r} ({what}, "
                    f"{leg['payload_bytes']} payload bytes) rides "
                    f"the wire as {leg['dtype']} "
                    f"({leg['itemsize']} B/elem) but the plan "
                    f"declares {kind}={want} ({want_size} B/elem) — "
                    f"the leg silently widened "
                    f"{leg['itemsize'] / want_size:.0f}x; route it "
                    "through the quantized collective family or fix "
                    "the plan's precision declaration", loc))
        # MXL802 — the stage-2 wire contract: grad sync must be
        # reduce-scatter + all-gather; a grad-sized ungated psum on
        # the dp axis moves the full gradient anyway.
        if zero_stage == 2 and kind == "dp_grad" and \
                leg["op"] == "all-reduce" and not leg["gated"] and \
                leg["payload_bytes"] >= _MXL802_FLOOR:
            findings.append(Finding(
                "MXL802",
                f"{owner}:{variant or 'step'}: a full all-reduce "
                f"({leg['payload_bytes']} payload bytes, "
                f"{leg['dtype']}) survives on the ZeRO-2 grad leg "
                f"over axis {ax!r} — stage 2 contracts "
                "reduce-scatter + all-gather (each member reduces "
                "only its shard); this psum moves the whole gradient "
                "and defeats the partitioning", loc))
        # MXL803 — an obs-only leg outside any lax.cond gate in a
        # variant registered as sampled: the health/integrity spec
        # says those rows ride the sampling gate.
        if rec.get("sampled") and kind == "stats" and \
                not leg["gated"]:
            findings.append(Finding(
                "MXL803",
                f"{owner}:{variant or 'step'}: an observability-only "
                f"{leg['primitive']} over axis {ax!r} "
                f"({leg['payload_bytes']} payload bytes) executes "
                "OUTSIDE the health plane's lax.cond(due) sampling "
                "gate — the variant is registered as sampled, so "
                "this row pays its wire cost every step; move it "
                "under the gate", loc))
    # MXL804 — static vs observatory bytes-on-wire (gated legs
    # included: collective_stats counts both cond branches in the
    # HLO text, so the static total must too).
    measured = measured_override if measured_override is not None \
        else _measured_wire(rec)
    if measured is not None and _reconcile_eligible(rec, legs,
                                                    derived):
        static = sum(leg["wire_bytes"] for leg in legs)
        if static or measured:
            ratio = abs(static - measured) / float(max(measured, 1))
            if ratio > drift:
                findings.append(Finding(
                    "MXL804",
                    f"{owner}:{variant or 'step'}: static "
                    f"bytes-on-wire {static} vs the observatory's "
                    f"runtime accounting {measured} for program "
                    f"{rec.get('program') or '(explicit)'} — "
                    f"{ratio:.0%} drift (> {drift:.0%}); either the "
                    "static wire model or the runtime counter is "
                    "lying, and both feed the compression-ratio "
                    "claims", loc))
    return findings


def analyze_wire(jaxpr=None, plan=None, *, drift: float = 0.10,
                 owner: str = "wire", kind: str = "train",
                 zero_stage: Optional[int] = None,
                 sampled: bool = False, obs_outputs=(),
                 mesh_axes=None,
                 measured_wire_bytes: Optional[int] = None
                 ) -> List[Finding]:
    """MXL801–804 — the wire audit (docs/static_analysis.md, "The
    wire auditor").

    Registry-driven by default: walks every variant the trainers and
    the serving plane registered via :func:`note_step` (free in a
    fresh process — the ``--self-check`` CI gate stays quiet).  The
    explicit ``(jaxpr, plan)`` entry point audits one closed jaxpr
    directly (the ``tools/mxwire.py lint`` / seeded-corpus path);
    ``measured_wire_bytes`` there supplies the observatory side of
    the MXL804 reconciliation, which otherwise reads the program
    record named at registration.
    """
    if jaxpr is not None:
        rec = {
            "owner": str(owner), "variant": "", "fn": None,
            "avals": (), "plan": plan,
            "mesh_axes": dict(mesh_axes) if mesh_axes else
            dict(getattr(plan, "axes", None) or {}),
            "dp_axis": str(getattr(plan, "dp_axis", "dp")),
            "zero_stage": int(
                zero_stage if zero_stage is not None
                else (getattr(plan, "zero_stage", None) or 0)),
            "compressed": False, "sampled": bool(sampled),
            "kind": str(kind), "program": None, "params_bytes": [],
            "obs_outputs": tuple(int(i)
                                 for i in (obs_outputs or ())),
            "jaxpr": jaxpr, "trace_error": None, "legs": None,
        }
        # an explicit caller handing us measured bytes opts into the
        # reconciliation even without a harvested program record
        if measured_wire_bytes is not None:
            rec["kind"] = rec["kind"] or "train"
        return _audit_one(rec, drift,
                          measured_override=measured_wire_bytes)
    findings: List[Finding] = []
    with _lock:
        recs = list(_variants.values())
    for rec in sorted(recs, key=lambda r: (r["owner"], r["variant"])):
        try:
            findings.extend(_audit_one(rec, drift))
        except Exception:
            # one untraceable/odd variant must not kill the gate
            continue
    return findings


# -- report (the CLI / bench surface) ---------------------------------------

def wire_report() -> Dict[str, dict]:
    """Per-variant leg inventory for ``tools/mxwire.py show`` and the
    bench ``wire`` block: ``{"owner:variant": {legs, static/measured
    wire bytes, drift, ...}}``."""
    out: Dict[str, dict] = {}
    with _lock:
        recs = list(_variants.values())
    for rec in sorted(recs, key=lambda r: (r["owner"], r["variant"])):
        key = f"{rec['owner']}:{rec['variant']}" if rec["variant"] \
            else rec["owner"]
        try:
            legs, derived = _legs_for(rec)
        except Exception:
            legs, derived = [], False
        static = sum(leg["wire_bytes"] for leg in legs)
        measured = _measured_wire(rec)
        row = {
            "kind": rec["kind"], "zero_stage": rec["zero_stage"],
            "compressed": rec["compressed"],
            "sampled": rec["sampled"], "derived": derived,
            "program": rec.get("program"),
            "trace_error": rec.get("trace_error"),
            "legs": [dict(leg) for leg in legs],
            "static_wire_bytes": int(static),
            "measured_wire_bytes": measured,
            "reconciled": _reconcile_eligible(rec, legs, derived)
            and measured is not None,
        }
        if row["reconciled"] and (static or measured):
            row["drift"] = abs(static - (measured or 0)) / float(
                max(measured or 0, 1))
        out[key] = row
    return out
