"""Graph passes over the Symbol IR (rules MXL1xx).

These are the Relay-style "static passes over the framework IR": every
check runs on the pure-Python ``_Node`` DAG (or its JSON serialization)
with NO device execution.  The shape/dtype contract validator abstract-
evaluates each node through ``jax.eval_shape`` via the same
``_propagate_shapes`` walk ``infer_shape`` uses, so a graph that lints
clean is guaranteed to bind clean for the validated shapes.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding

__all__ = ["analyze_symbol", "analyze_graph_json", "node_path"]


def _frontend_custom_ops():
    """Ops whose symbol nodes are built by frontend glue (Dropout adds the
    RNG key input at eval time, BatchNorm appends mode flags): their node
    arity/attrs intentionally differ from the raw OpDef."""
    from ..ndarray import _CUSTOM
    return set(_CUSTOM)


def node_path(node, limit: int = 8) -> str:
    """Human diagnostic path: a chain of node names from a graph input to
    ``node`` (first-input chain), e.g. ``data -> fc0 -> relu1``."""
    chain = [node]
    cur = node
    seen = {id(node)}
    while cur.inputs:
        cur = cur.inputs[0][0]
        if id(cur) in seen:  # cyclic graph: stop rather than loop
            break
        seen.add(id(cur))
        chain.append(cur)
    names = [n.name for n in reversed(chain)]
    if len(names) > limit:
        names = names[:2] + ["..."] + names[-(limit - 3):]
    return " -> ".join(names)


def _collect_nodes(heads) -> List:
    """All nodes reachable from ``heads`` — cycle-safe (plain visited-set
    walk; no ordering guarantee on cyclic graphs)."""
    seen = set()
    order = []
    stack = list(heads)
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        order.append(node)
        for inp, _ in node.inputs:
            stack.append(inp)
    return order


def _find_cycle(heads) -> Optional[List]:
    """Three-color DFS; returns the cycle's nodes in order, or None.
    A back edge targets a GRAY node, which by construction sits on the
    current DFS stack — the cycle is the stack suffix from it."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[int, int] = {}

    for head in heads:
        if color.get(id(head), WHITE) != WHITE:
            continue
        stack = [(head, iter(head.inputs))]
        color[id(head)] = GRAY
        while stack:
            node, it = stack[-1]
            advanced = False
            for inp, _ in it:
                c = color.get(id(inp), WHITE)
                if c == GRAY:
                    k = next(i for i, (fn, _) in enumerate(stack)
                             if fn is inp)
                    return [fn for fn, _ in stack[k:]]
                if c == WHITE:
                    color[id(inp)] = GRAY
                    stack.append((inp, iter(inp.inputs)))
                    advanced = True
                    break
            if not advanced:
                color[id(node)] = BLACK
                stack.pop()
    return None


def _registry_node_checks(nodes, anchor: str) -> List[Finding]:
    from ..ops.registry import get_op, _signature_facts
    out: List[Finding] = []
    custom = _frontend_custom_ops()
    varkw_by_op: Dict[str, bool] = {}
    for node in nodes:
        if node.op is None or node.op in custom:
            continue
        try:
            op = get_op(node.op)
        except KeyError:
            out.append(Finding(
                "MXL106", f"node {node.name!r} uses unregistered operator "
                f"{node.op!r}", f"{anchor}:{node_path(node)}"))
            continue
        if op.num_inputs is not None and len(node.inputs) != op.num_inputs:
            out.append(Finding(
                "MXL107", f"node {node.name!r} ({node.op}) has "
                f"{len(node.inputs)} inputs; registry declares "
                f"{op.num_inputs}", f"{anchor}:{node_path(node)}"))
        known = set(op.attr_names) | set(op.scalar_attrs)
        has_varkw = varkw_by_op.get(node.op)
        if has_varkw is None:
            facts = _signature_facts(op.fcompute)
            # introspection failure means we cannot judge attrs: skip
            has_varkw = True if facts is None else facts[2]
            varkw_by_op[node.op] = has_varkw
        if not has_varkw:
            for attr in node.attrs:
                if attr not in known:
                    out.append(Finding(
                        "MXL108",
                        f"node {node.name!r} ({node.op}) carries attr "
                        f"{attr!r} unknown to the op (known: "
                        f"{sorted(known)})",
                        f"{anchor}:{node_path(node)}"))
    return out


def _shape_checks(sym, shapes: Optional[dict], anchor: str) -> List[Finding]:
    from ..symbol.symbol import _propagate_shapes, _topo
    out: List[Finding] = []
    shapes = dict(shapes or {})
    # honor var(shape=) hints exactly like infer_shape does (inside
    # _propagate_shapes); afterwards report what could not be validated
    out_shapes: Dict[Tuple[int, int], tuple] = {}
    errored = set()

    def on_err(node, ins, exc):
        errored.add(id(node))
        msg = str(exc).strip().splitlines()
        msg = msg[0] if msg else type(exc).__name__
        if len(msg) > 220:
            msg = msg[:220] + "..."
        in_desc = ", ".join("?" if s is None else str(tuple(s))
                            for s in ins)
        out.append(Finding(
            "MXL105", f"node {node.name!r} ({node.op}) rejects input "
            f"shapes [{in_desc}]: {msg}", f"{anchor}:{node_path(node)}"))

    _propagate_shapes(sym, shapes, on_node_error=on_err,
                      out_shapes=out_shapes)

    unvalidated = []
    for node in _topo(sym._head_nodes()):
        if node.op is None or id(node) in errored:
            continue
        if (id(node), 0) not in out_shapes:
            unvalidated.append(node.name)
    if unvalidated:
        shown = unvalidated[:6]
        more = f" (+{len(unvalidated) - 6} more)" \
            if len(unvalidated) > 6 else ""
        out.append(Finding(
            "MXL109", "input shapes unknown; nodes not shape-validated: "
            + ", ".join(shown) + more, anchor))
    return out


def analyze_symbol(sym, shapes: Optional[dict] = None,
                   check_shapes: bool = True,
                   name: str = "graph") -> List[Finding]:
    """Run every graph pass over a Symbol.

    ``shapes``: optional {input_name: shape} for the contract validator
    (``var(shape=)`` hints are honored automatically).  Returns findings;
    an empty list means the graph lints clean.
    """
    heads = sym._head_nodes()
    findings: List[Finding] = []

    cyc = _find_cycle(heads)
    if cyc is not None:
        path = " -> ".join(n.name for n in cyc) + f" -> {cyc[0].name}"
        findings.append(Finding(
            "MXL101", f"cycle: {path}", f"{name}:{cyc[0].name}"))

    nodes = _collect_nodes(heads)
    by_name: Dict[str, int] = {}
    for n in nodes:
        by_name[n.name] = by_name.get(n.name, 0) + 1
    for nm, cnt in sorted(by_name.items()):
        if cnt > 1:
            findings.append(Finding(
                "MXL102", f"{cnt} nodes share the name {nm!r}; "
                "save/load and arg binding key on unique names",
                f"{name}:{nm}"))

    findings.extend(_registry_node_checks(nodes, name))

    # abstract evaluation only makes sense on a structurally sound DAG
    structural_errors = any(f.rule in ("MXL101", "MXL106", "MXL107")
                            for f in findings)
    if check_shapes and not structural_errors:
        findings.extend(_shape_checks(sym, shapes, name))
    return findings


def analyze_graph_json(json_str: str, shapes: Optional[dict] = None,
                       check_shapes: bool = True,
                       name: str = "<json>") -> List[Finding]:
    """Lint a serialized graph (``Symbol.tojson`` / ``save`` output).

    Runs the structural passes directly on the node table — so cycles and
    dangling references that ``load_json`` itself would choke on are
    reported as findings, not exceptions — then (if sound) loads the
    Symbol and runs the registry/shape passes.
    """
    findings: List[Finding] = []
    try:
        data = json.loads(json_str)
        nodes = data["nodes"]
        heads = data["heads"]
    except (ValueError, KeyError, TypeError) as e:
        return [Finding("MXL110", f"unparseable graph JSON: {e}", name)]
    if not isinstance(nodes, list) or not isinstance(heads, list):
        return [Finding("MXL110", "'nodes' and 'heads' must be lists",
                        name)]

    def _ref_index(ref):
        """Node index of an [idx, out_idx, version] edge ref, or None."""
        if isinstance(ref, (list, tuple)) and ref and \
                isinstance(ref[0], int) and 0 <= ref[0] < n:
            return ref[0]
        return None

    n = len(nodes)
    edges: List[List[int]] = []
    for i, jn in enumerate(nodes):
        if not isinstance(jn, dict):
            findings.append(Finding(
                "MXL110", f"node entry {i} is not an object: {jn!r}",
                f"{name}:node{i}"))
            edges.append([])
            continue
        ins = []
        for ref in jn.get("inputs", []):
            j = _ref_index(ref)
            if j is None:
                findings.append(Finding(
                    "MXL110", f"node {jn.get('name', i)!r} has a bad "
                    f"input ref {ref!r}", f"{name}:node{i}"))
            else:
                ins.append(j)
        edges.append(ins)
    for ref in heads:
        if _ref_index(ref) is None:
            findings.append(Finding(
                "MXL110", f"bad head ref {ref!r}", name))
    if any(f.rule == "MXL110" for f in findings):
        return findings

    # cycle over the index graph (serialized graphs may be hand-edited)
    WHITE, GRAY, BLACK = 0, 1, 2
    color = [WHITE] * n
    cyc_at = None
    for s in range(n):
        if color[s] != WHITE:
            continue
        stack = [(s, iter(edges[s]))]
        color[s] = GRAY
        while stack and cyc_at is None:
            i, it = stack[-1]
            advanced = False
            for j in it:
                if color[j] == GRAY:
                    cyc_at = j
                    break
                if color[j] == WHITE:
                    color[j] = GRAY
                    stack.append((j, iter(edges[j])))
                    advanced = True
                    break
            if cyc_at is None and not advanced:
                color[i] = BLACK
                stack.pop()
        if cyc_at is not None:
            break
    if cyc_at is not None:
        findings.append(Finding(
            "MXL101", f"cycle through node "
            f"{nodes[cyc_at].get('name', cyc_at)!r}",
            f"{name}:{nodes[cyc_at].get('name', cyc_at)}"))
        return findings  # load_json would recurse into the cycle

    # reachability from heads
    reach = set()
    stack = [ref[0] for ref in heads]
    while stack:
        i = stack.pop()
        if i in reach:
            continue
        reach.add(i)
        stack.extend(edges[i])
    for i, jn in enumerate(nodes):
        if i in reach:
            continue
        if jn.get("op", "null") == "null":
            findings.append(Finding(
                "MXL104", f"variable {jn.get('name', i)!r} is not consumed "
                "by any head", f"{name}:{jn.get('name', i)}"))
        else:
            findings.append(Finding(
                "MXL103", f"node {jn.get('name', i)!r} "
                f"({jn.get('op')}) is unreachable from every head",
                f"{name}:{jn.get('name', i)}"))

    from ..symbol.symbol import load_json
    try:
        sym = load_json(json_str)
    except Exception as e:
        findings.append(Finding(
            "MXL110", f"load_json failed: {e}", name))
        return findings
    findings.extend(analyze_symbol(sym, shapes=shapes,
                                   check_shapes=check_shapes, name=name))
    return findings
