"""Runtime-hazard pass (rule MXL4xx): jit-cache key blowup.

The static source pass (MXL303) predicts retrace storms; this pass
*observes* them: after running a workload, ``engine.cache_info()`` shows
how many distinct executables each op compiled.  An op with many cache
entries whose keys differ only in the values of one or two attrs is
recompiling per value — the attr should ride the dynamic-scalar path
(``scalar_attrs``) or be hoisted to a constant.
"""
from __future__ import annotations

from typing import List

from .findings import Finding

__all__ = ["analyze_cache"]


def analyze_cache(threshold: int = 8) -> List[Finding]:
    """Flag ops whose jit-cache entry count exceeds ``threshold``.

    Shape/dtype-driven re-specialization also multiplies entries (that is
    healthy and unavoidable), so the message names the varying attrs when
    the blowup is attributable to attr values — the actionable case.
    """
    from .. import engine
    info = engine.cache_info()
    findings: List[Finding] = []
    for name, sigs in sorted(info["ops"].items()):
        if len(sigs) <= threshold:
            continue
        # which attr names take multiple distinct values across entries?
        values_by_attr = {}
        for sig in sigs:
            try:
                items = list(sig)
            except TypeError:
                items = []
            for kv in items:
                if isinstance(kv, tuple) and len(kv) == 2:
                    values_by_attr.setdefault(kv[0], set()).add(kv[1])
        varying = sorted(a for a, vals in values_by_attr.items()
                         if len(vals) > 1)
        detail = (f"; attr(s) {', '.join(varying)} vary across entries — "
                  "candidates for scalar_attrs") if varying else \
            " (distinct attr signatures; check call sites)"
        findings.append(Finding(
            "MXL401", f"op {name!r} holds {len(sigs)} compiled cache "
            f"entries (threshold {threshold}){detail}",
            f"cache:{name}"))
    return findings
