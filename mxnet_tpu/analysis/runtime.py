"""Runtime-hazard passes: observed dispatch/cache pathologies.

The static source passes predict hazards; these passes *observe* them
after a workload ran:

* MXL401 — jit-cache key blowup via ``engine.cache_info()``: an op with
  many cache entries whose keys differ only in one or two attr values is
  recompiling per value; the attr should ride the dynamic-scalar path
  (``scalar_attrs``) or be hoisted to a constant.
* MXL305 — silent CompiledStep degradation: a training loop that asked
  for the one-dispatch compiled step but is actually running per-op
  eager dispatches (non-hybridizable forward, optimizer without a fused
  program, ...).  The finding carries the recorded fallback reason.
"""
from __future__ import annotations

from typing import List

from .findings import Finding

__all__ = ["analyze_cache", "analyze_compiled_steps"]


def analyze_cache(threshold: int = 8) -> List[Finding]:
    """Flag ops whose jit-cache entry count exceeds ``threshold``.

    Shape/dtype-driven re-specialization also multiplies entries (that is
    healthy and unavoidable), so the message names the varying attrs when
    the blowup is attributable to attr values — the actionable case.
    """
    from .. import engine
    info = engine.cache_info()
    findings: List[Finding] = []
    for name, sigs in sorted(info["ops"].items()):
        if len(sigs) <= threshold:
            continue
        # which attr names take multiple distinct values across entries?
        values_by_attr = {}
        for sig in sigs:
            try:
                items = list(sig)
            except TypeError:
                items = []
            for kv in items:
                if isinstance(kv, tuple) and len(kv) == 2:
                    values_by_attr.setdefault(kv[0], set()).add(kv[1])
        varying = sorted(a for a, vals in values_by_attr.items()
                         if len(vals) > 1)
        detail = (f"; attr(s) {', '.join(varying)} vary across entries — "
                  "candidates for scalar_attrs") if varying else \
            " (distinct attr signatures; check call sites)"
        findings.append(Finding(
            "MXL401", f"op {name!r} holds {len(sigs)} compiled cache "
            f"entries (threshold {threshold}){detail}",
            f"cache:{name}"))
    return findings


def analyze_compiled_steps() -> List[Finding]:
    """One MXL305 finding per CompiledStep that silently fell back to
    the eager per-op path this process (``compiled_step.
    fallback_reports()``).  The explicit ``MXTPU_COMPILED_STEP=0``
    escape hatch never reports — only surprising degradations do."""
    from ..gluon import compiled_step as _cs
    return [
        Finding("MXL305",
                f"compiled train step {name!r} silently fell back to "
                f"the eager per-op path: {reason}",
                f"step:{name}")
        for name, reason in _cs.fallback_reports()]
